"""Compact moment-summary backend: ~100 bytes/stream, maxent quantiles.

The moments sketch (arXiv:1803.01969) shows that for high-cardinality
aggregation a quantile summary need not store bins at all: ``k`` power
sums plus min/max/count support quantile estimation via a
maximum-entropy density solve, merge by pure addition, and cost ~100
bytes per stream -- two orders of magnitude under the dense store's
``n_bins * 4`` bytes.  This module is that contract behind the same
seams:

* **State** (:class:`MomentState`): per stream ``count``,
  ``zero_count``, ``neg_count``, ``sum``, ``min``, ``max`` plus ``k``
  raw power sums of the nonzero values AND ``k`` power sums of
  ``ln |v|`` (the paper's log-moments variant -- the accurate basis for
  the long-tailed distributions sketches exist for).  All f32 on
  device: ``(6 + 2k) * 4`` bytes/stream = 104 bytes at the default
  ``k = 12``.
* **Ingest** (:func:`add`) is ONE fused device dispatch: masks route
  zeros/NaN/padding exactly like the dense tier, and the power sums
  build by ``k`` fused multiply-accumulates over the batch.
* **Merge** is elementwise addition (+ min/min, max/max), so
  :func:`merge`, :func:`merge_axis`, :func:`psum_merge`, and
  :func:`fold_hosts` are trivial and bit-exact across topologies.
* **Query** (:func:`quantile`) runs on the HOST: standardized moments
  (f64, binomial shift to [-1, 1]) -> Chebyshev moments -> Newton
  solve of the maxent dual on a fixed grid -> CDF inversion, with a
  documented fallback ladder (fewer moments -> uniform density) when
  the solve cannot converge.  Zeros re-enter as a point mass at 0.

Error envelope (documented, test-pinned on the ``tests/datasets.py``
distributions): uniform / lognormal / pareto streams answer p5..p99
within a few percent relative error at ``k = 12`` -- far looser than
the dense alpha contract, which is exactly the trade the ~100x memory
saving buys.  The raw-power basis (used when a stream holds
non-positive values) loses fidelity when ``max - min`` spans more than
~3 decades (f32 power sums saturate); the log basis (all-positive
streams) has no such limit.

Failure modes: empty streams answer NaN; a failed maxent solve falls
back down the moment ladder (counted via
``backend.moment_fallbacks``), never raises; merging unequal specs
raises ``UnequalSketchParametersError``; fractional-weight and
mixed-sign contracts are as documented above; f32 counters share the
dense tier's 2**24 exact-accumulation ceiling.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sketches_tpu import telemetry
from sketches_tpu.batched import DEFAULT_REL_ACC, SketchSpec
from sketches_tpu.mapping import zero_threshold as mapping_zero_threshold
from sketches_tpu.resilience import SpecError

__all__ = [
    "MomentState",
    "MomentDDSketch",
    "init",
    "add",
    "merge",
    "merge_axis",
    "psum_merge",
    "fold_hosts",
    "quantile",
    "bytes_per_stream",
]

#: CDF grid resolution of the maxent solve (the paper uses a fixed
#: Chebyshev grid too; 512 points bounds the inversion error at ~0.2%
#: of the support per step, far under the moment-truncation error).
_GRID = 512

_MAX_NEWTON = 60


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MomentState:
    """Per-batch moment-summary state (struct-of-arrays, all f32).

    ``powers[:, i]`` is the weighted sum of ``v**(i+1)`` over nonzero
    finite values (either sign); ``log_powers[:, i]`` the weighted sum
    of ``ln|v| ** (i+1)`` over the same lanes.  ``min``/``max`` are
    +/-inf for empty streams (the dense tier's convention); NaN values
    poison ``sum`` and count into the zero bucket exactly like
    :func:`sketches_tpu.batched.add`.
    """

    count: jax.Array  # [n_streams] total weight (incl. zeros/NaN)
    zero_count: jax.Array  # [n_streams]
    neg_count: jax.Array  # [n_streams] weight of v < 0 lanes
    sum: jax.Array  # [n_streams]
    min: jax.Array  # [n_streams]
    max: jax.Array  # [n_streams]
    powers: jax.Array  # [n_streams, k]
    log_powers: jax.Array  # [n_streams, k]

    @property
    def n_streams(self) -> int:
        return self.count.shape[-1]

    @property
    def n_moments(self) -> int:
        return self.powers.shape[-1]


def init(spec: SketchSpec, n_streams: int) -> MomentState:
    """Allocate an empty moment batch (``spec.n_moments`` power sums).
    Empty streams answer NaN from :func:`quantile` until mass arrives."""
    k = spec.n_moments
    dt = spec.dtype
    z1 = jnp.zeros((n_streams,), dt)
    return MomentState(
        count=z1,
        zero_count=jnp.zeros_like(z1),
        neg_count=jnp.zeros_like(z1),
        sum=jnp.zeros_like(z1),
        min=jnp.full((n_streams,), jnp.inf, dt),
        max=jnp.full((n_streams,), -jnp.inf, dt),
        powers=jnp.zeros((n_streams, k), dt),
        log_powers=jnp.zeros((n_streams, k), dt),
    )


def bytes_per_stream(spec: SketchSpec) -> int:
    """Device bytes per stream of the moment state (the contract the
    backend exists for; ``<= 256`` at every legal ``n_moments``).
    Never raises."""
    itemsize = jnp.dtype(spec.dtype).itemsize
    return (6 + 2 * spec.n_moments) * itemsize


def add(
    spec: SketchSpec,
    mstate: MomentState,
    values,
    weights=None,
) -> MomentState:
    """Ingest ``values[n_streams, S]`` in ONE fused device dispatch.

    Pure function (jit with donation on ``mstate``).  Lane routing
    matches the dense tier: ``weights <= 0`` is inert padding, ``|v|``
    under the dtype's smallest normal takes the zero path, NaN counts
    into the zero path and poisons ``sum``.  Power sums accumulate by
    ``k`` fused multiply-accumulates -- no scatter, no bins.
    """
    v = jnp.asarray(values).astype(spec.dtype)
    if v.ndim == 1:
        v = v[:, None]
    if weights is None:
        w = jnp.ones_like(v)
    else:
        w = jnp.broadcast_to(jnp.asarray(weights, spec.dtype), v.shape)
    live = w > 0
    tiny = jnp.asarray(mapping_zero_threshold(v.dtype), v.dtype)
    absv = jnp.abs(v)
    routable = jnp.logical_and(live, absv >= tiny)  # NaN fails -> zero path
    zeroish = jnp.logical_and(live, jnp.logical_not(absv >= tiny))
    wl = jnp.where(routable, w, 0)
    x = jnp.where(routable, v, 0)
    lx = jnp.log(jnp.where(routable, absv, jnp.asarray(1.0, v.dtype)))
    p_terms = []
    l_terms = []
    xt = jnp.ones_like(v)
    lt = jnp.ones_like(v)
    for _ in range(spec.n_moments):
        xt = xt * x
        lt = lt * lx
        p_terms.append((wl * xt).sum(-1))
        l_terms.append((wl * lt).sum(-1))
    inf = jnp.asarray(jnp.inf, spec.dtype)
    finite_live = jnp.logical_and(live, jnp.logical_not(jnp.isnan(v)))
    w_live = jnp.where(live, w, 0)
    return MomentState(
        count=mstate.count + w_live.sum(-1),
        zero_count=mstate.zero_count + jnp.where(zeroish, w, 0).sum(-1),
        neg_count=mstate.neg_count
        + jnp.where(jnp.logical_and(routable, v < 0), w, 0).sum(-1),
        sum=mstate.sum + (jnp.where(live, v, 0) * w_live).sum(-1),
        min=jnp.minimum(mstate.min, jnp.where(finite_live, v, inf).min(-1)),
        max=jnp.maximum(mstate.max, jnp.where(finite_live, v, -inf).max(-1)),
        powers=mstate.powers + jnp.stack(p_terms, axis=-1),
        log_powers=mstate.log_powers + jnp.stack(l_terms, axis=-1),
    )


def merge(spec: SketchSpec, a: MomentState, b: MomentState) -> MomentState:
    """Merged batch == having ingested both streams (elementwise adds,
    min/min, max/max).  Bit-exact up to f32 addition rounding; empty
    operands are exact identities.  Pure function."""
    return MomentState(
        count=a.count + b.count,
        zero_count=a.zero_count + b.zero_count,
        neg_count=a.neg_count + b.neg_count,
        sum=a.sum + b.sum,
        min=jnp.minimum(a.min, b.min),
        max=jnp.maximum(a.max, b.max),
        powers=a.powers + b.powers,
        log_powers=a.log_powers + b.log_powers,
    )


def merge_axis(spec: SketchSpec, mstate: MomentState, axis: int = 0):
    """Reduce stacked ``[K, n_streams, ...]`` partials over ``axis``
    (the tree-reduction form of :func:`merge`; empty stacks are a
    caller error and raise through jnp)."""
    return MomentState(
        count=mstate.count.sum(axis),
        zero_count=mstate.zero_count.sum(axis),
        neg_count=mstate.neg_count.sum(axis),
        sum=mstate.sum.sum(axis),
        min=mstate.min.min(axis),
        max=mstate.max.max(axis),
        powers=mstate.powers.sum(axis),
        log_powers=mstate.log_powers.sum(axis),
    )


def psum_merge(mstate: MomentState, axis_name) -> MomentState:
    """Collective form of :func:`merge` over mesh axes (must run inside
    ``shard_map``/pmap; a tuple of axes folds innermost-first like the
    dense tier's hierarchical fold).  Sums psum, extrema pmin/pmax --
    bit-exact for the integer-valued counters, f32-rounded sums as
    documented."""
    from jax import lax

    from sketches_tpu.parallel import _value_axes

    for ax in reversed(_value_axes(axis_name)):
        mstate = MomentState(
            count=lax.psum(mstate.count, ax),
            zero_count=lax.psum(mstate.zero_count, ax),
            neg_count=lax.psum(mstate.neg_count, ax),
            sum=lax.psum(mstate.sum, ax),
            min=lax.pmin(mstate.min, ax),
            max=lax.pmax(mstate.max, ax),
            powers=lax.psum(mstate.powers, ax),
            log_powers=lax.psum(mstate.log_powers, ax),
        )
    return mstate


def fold_hosts(spec: SketchSpec, mstates: Sequence[MomentState],
               reachable=None):
    """Cross-host fold of per-host moment partials ->
    ``(folded MomentState, ShardLossReport)``.

    Same protocol shape as the dense :func:`sketches_tpu.parallel.fold_hosts`:
    unreachable hosts (explicit mask, or the armed ``dcn.partition``
    fault site) are folded AROUND with their mass accounted in the
    report -- detected, never silently zeroed; no host reachable raises
    ``ShardLossError``; an empty or shape-mismatched stack raises
    ``SketchValueError``.
    """
    from sketches_tpu import faults, resilience
    from sketches_tpu.resilience import (
        ShardLossError,
        ShardLossReport,
        SketchValueError,
    )

    n_hosts = len(mstates)
    if n_hosts == 0:
        raise SketchValueError("fold_hosts needs at least one host state")
    shapes = {tuple(st.powers.shape) for st in mstates}
    if len(shapes) != 1:
        raise SketchValueError(
            f"fold_hosts needs equal-shape host states; got {shapes}"
        )
    if reachable is None:
        reach = np.ones((n_hosts,), bool)
        part = faults.partitioned_hosts(n_hosts) if faults._ACTIVE else ()
        if part:
            reach[list(part)] = False
    else:
        reach = np.asarray(reachable, bool).reshape(-1)
        if reach.shape[0] != n_hosts:
            raise SketchValueError(
                f"reachable mask length {reach.shape[0]} != {n_hosts} hosts"
            )
    if not reach.any():
        raise ShardLossError(
            f"all {n_hosts} hosts unreachable across DCN; nothing to fold"
        )
    live = [st for st, r in zip(mstates, reach) if r]
    folded = live[0]
    for st in live[1:]:
        folded = merge(spec, folded, st)
    counts = np.stack(
        [np.asarray(jax.device_get(st.count), np.float64) for st in mstates]
    )
    report = ShardLossReport(
        live=reach,
        surviving_count=counts[reach].sum(0),
        dropped_count=counts[~reach].sum(0),
    )
    if not reach.all():
        resilience.bump("dcn.partitions", int((~reach).sum()))
    return folded, report


# ---------------------------------------------------------------------------
# Host-side maximum-entropy quantile solve
# ---------------------------------------------------------------------------


def _std_power_moments(sums: np.ndarray, mass: float, c: float, s: float,
                       k: int) -> np.ndarray:
    """Raw power sums -> standardized moments ``E[((t-c)/s)**j]``,
    ``j = 0..k`` (f64 binomial shift; the classic msketch conversion).
    Returns NaN-free prefix only -- the caller trims at the first
    non-finite entry."""
    e = np.empty(k + 1, np.float64)
    e[0] = 1.0
    e[1:] = sums[:k] / mass
    out = np.empty(k + 1, np.float64)
    for j in range(k + 1):
        acc = 0.0
        for i in range(j + 1):
            acc += math.comb(j, i) * e[i] * (-c) ** (j - i)
        out[j] = acc / s**j
    return out


def _cheb_moments(std: np.ndarray) -> np.ndarray:
    """Standardized power moments -> Chebyshev moments ``E[T_j(y)]``
    (exact linear map; f64)."""
    from numpy.polynomial import chebyshev as C

    k = std.shape[0] - 1
    out = np.empty(k + 1, np.float64)
    for j in range(k + 1):
        coef = C.cheb2poly(np.eye(j + 1, dtype=np.float64)[j])
        out[j] = float((coef * std[: coef.shape[0]]).sum())
    return out


def _maxent_density(mu: np.ndarray) -> Optional[np.ndarray]:
    """Newton-solve the maxent dual for Chebyshev moments ``mu`` ->
    grid density ``[|_GRID|]`` (normalized to sum 1), or None when the
    solve fails to converge (the caller falls back to fewer moments)."""
    from numpy.polynomial import chebyshev as C

    k = mu.shape[0] - 1
    y = (np.arange(_GRID, dtype=np.float64) + 0.5) / _GRID * 2.0 - 1.0
    dy = 2.0 / _GRID
    t = C.chebvander(y, k)  # [_GRID, k+1]
    del dy  # normalization is explicit below; the measure scale cancels
    lam = np.zeros(k, np.float64)  # lambda_1..k; T_0's weight = log Z
    t1 = t[:, 1:]
    for _ in range(_MAX_NEWTON):
        logp = t1 @ lam
        logp -= logp.max()  # overflow guard
        p = np.exp(logp)
        p /= p.sum()  # probability masses on the grid
        e_t = (t1 * p[:, None]).sum(0)  # E_p[T_j], j=1..k
        g = e_t - mu[1:]
        if not np.all(np.isfinite(g)):
            return None
        if np.abs(g).max() < 1e-9:
            return p
        # Newton on the normalized dual: Hessian = Cov_p[T_i, T_j].
        h = (t1.T * p) @ t1 - np.outer(e_t, e_t)
        h += np.eye(k) * 1e-10
        try:
            step = np.linalg.solve(h, g)
        except np.linalg.LinAlgError:
            return None
        norm = np.abs(step).max()
        if norm > 4.0:  # damping: long steps overshoot the dual
            step *= 4.0 / norm
        lam -= step
    logp = t1 @ lam
    p = np.exp(logp - logp.max())
    if not np.all(np.isfinite(p)) or p.sum() <= 0:
        return None
    return p / p.sum()


def _finite_prefix(arr: np.ndarray) -> int:
    """Length of the leading finite run (f32 power sums can saturate at
    high orders; the solver uses only the trustworthy prefix)."""
    bad = ~np.isfinite(arr)
    return int(np.argmax(bad)) if bad.any() else arr.shape[0]


#: Relative error budget of the f32-accumulated power sums (rounding
#: per fused add, batch reductions, merges; measured ~1e-6 end to end,
#: budgeted with slack).
_F32_SUM_ERR = 3e-6

#: Largest Chebyshev-moment absolute error the maxent solve tolerates
#: before a moment order does more harm than good.
_MOMENT_TOL = 5e-3


def _trusted_order(a: float, b: float, k: int) -> int:
    """Highest moment order whose Chebyshev moment survives f32 noise.

    Two amplifiers sit between the device's f32 power sums and the
    solver's Chebyshev moments: the binomial standardization shift
    (``((M + |c|) / s) ** j`` with ``M = max(|a|, |b|)``) and the
    power->Chebyshev conversion (leading coefficient ``2**(j-1)``).
    Orders whose amplified noise exceeds :data:`_MOMENT_TOL` are noise,
    not signal -- fitting them makes the density strictly worse (the
    observed failure mode on log-asymmetric supports like
    ``uniform(1, 100)``).  Symmetric supports (``c ~ 0``, e.g.
    lognormal in log space) keep their full order.  Always >= 2.
    """
    c, s = (a + b) / 2.0, (b - a) / 2.0
    if s <= 0:
        return 2
    amp = (max(abs(a), abs(b)) + abs(c)) / s
    order = 2
    for j in range(2, k + 1):
        if _F32_SUM_ERR * (amp**j) * (2.0 ** max(j - 1, 0)) > _MOMENT_TOL:
            break
        order = j
    return order


def _stream_quantiles(
    k: int, count: float, zero: float, neg: float, vmin: float,
    vmax: float, powers: np.ndarray, log_powers: np.ndarray,
    qs: np.ndarray,
) -> Tuple[np.ndarray, bool]:
    """One stream's maxent quantiles -> ``(values[Q], used_fallback)``.

    NaN row for an empty stream; zero-only streams answer 0; constant
    streams answer the constant.  The basis is log-moments for
    all-positive streams (the accurate choice for long tails), raw
    power moments otherwise.
    """
    if not count > 0:
        return np.full(qs.shape, np.nan), False
    nz = count - zero
    if not nz > 0:  # all mass in the zero bucket
        return np.zeros(qs.shape), False
    if not (np.isfinite(vmin) and np.isfinite(vmax)):
        return np.full(qs.shape, np.nan), False
    use_log = vmin > 0.0
    if use_log:
        a, b = math.log(vmin), math.log(vmax)
        sums = log_powers
    else:
        a, b = vmin, vmax
        sums = powers
    fallback = False
    if b - a < 1e-12 * max(1.0, abs(a)):
        density = np.full(_GRID, 1.0 / _GRID)
        a = b = (a + b) / 2.0
        grid = np.full(_GRID, a)
    else:
        c, s = (a + b) / 2.0, (b - a) / 2.0
        kk = min(k, _finite_prefix(sums), _trusted_order(a, b, k))
        density = None
        while kk >= 2:
            std = _std_power_moments(sums, nz, c, s, kk)
            if np.all(np.isfinite(std)):
                mu = _cheb_moments(std)
                density = _maxent_density(mu)
                if density is not None:
                    break
            fallback = True
            kk //= 2
        if density is None:  # 0-moment maxent: uniform on [a, b]
            fallback = True
            density = np.full(_GRID, 1.0 / _GRID)
        y = (np.arange(_GRID, dtype=np.float64) + 0.5) / _GRID * 2.0 - 1.0
        grid = c + s * y
    if use_log:
        grid = np.exp(grid)
    # Mixture CDF over sorted support: continuous part (weight nz) plus
    # a point mass at 0 (weight zero).  ``grid`` is increasing in value
    # space for both bases (exp is monotone).
    w = density * nz
    if zero > 0:
        pos = int(np.searchsorted(grid, 0.0))
        grid = np.insert(grid, pos, 0.0)
        w = np.insert(w, pos, zero)
    cdf = np.cumsum(w) / count
    idx = np.searchsorted(cdf, np.clip(qs, 0.0, 1.0), side="left")
    idx = np.clip(idx, 0, grid.shape[0] - 1)
    out = grid[idx]
    valid = (qs >= 0.0) & (qs <= 1.0)
    return np.where(valid, out, np.nan), fallback


def quantile(spec: SketchSpec, mstate: MomentState, qs) -> np.ndarray:
    """Quantile values for ``qs[Q]`` across the batch -> ``[n_streams, Q]``.

    Host-side solve (one maxent Newton per nonempty stream -- the
    moment backend trades query CPU for ~100x less device memory);
    empty streams and out-of-range q answer NaN; failed solves fall
    back down the moment ladder (counted, never raised).  Accuracy is
    the documented moment-truncation envelope, NOT the dense alpha
    contract.
    """
    qs_arr = np.atleast_1d(np.asarray(qs, np.float64))
    host = jax.device_get(
        (mstate.count, mstate.zero_count, mstate.neg_count, mstate.min,
         mstate.max, mstate.powers, mstate.log_powers)
    )
    count, zero, neg, vmin, vmax, powers, log_powers = (
        np.asarray(x, np.float64) for x in host
    )
    n = count.shape[0]
    out = np.empty((n, qs_arr.shape[0]), np.float64)
    n_fallback = 0
    for i in range(n):
        out[i], fb = _stream_quantiles(
            int(mstate.n_moments), float(count[i]), float(zero[i]),
            float(neg[i]), float(vmin[i]), float(vmax[i]), powers[i],
            log_powers[i], qs_arr,
        )
        n_fallback += bool(fb)
    if telemetry._ACTIVE:
        telemetry.counter_inc("backend.moment_solves", float(n))
        if n_fallback:
            telemetry.counter_inc(
                "backend.moment_fallbacks", float(n_fallback)
            )
    return out.astype(np.dtype(jnp.dtype(spec.dtype).name))


class MomentDDSketch:
    """Stateful facade for the moment-summary backend.

    Reference-shaped API (``add`` / ``merge`` / ``get_quantile_values``)
    over :class:`MomentState`; ingest is one fused jit dispatch with
    state donation, queries run the host maxent solve.  There is no
    engine ladder -- the single engine reports tier ``"moment"``
    through :meth:`get_quantile_values_resolved` and ignores tier
    exclusions (it is its own floor).

    Failure modes: empty streams answer NaN; failed solves fall back
    (counted), never raise; merging unequal specs raises
    ``UnequalSketchParametersError``; invalid construction raises
    ``SpecError``; see the module docstring for the accuracy envelope
    and the mixed-sign/raw-basis range caveat.
    """

    def __init__(
        self,
        n_streams: int,
        relative_accuracy: float = DEFAULT_REL_ACC,
        n_moments: Optional[int] = None,
        spec: Optional[SketchSpec] = None,
        state: Optional[MomentState] = None,
        engine: str = "auto",  # accepted for facade parity; single engine
    ):
        if spec is None:
            spec = SketchSpec(
                relative_accuracy=relative_accuracy,
                backend="moment",
                n_moments=12 if n_moments is None else n_moments,
            )
        if spec.backend != "moment":
            raise SpecError(
                f"MomentDDSketch needs backend='moment'; got"
                f" {spec.backend!r}"
            )
        self.spec = spec
        self._state = init(spec, n_streams) if state is None else state
        self._add = jax.jit(
            functools.partial(add, spec), donate_argnums=(0,)
        )
        self._merge = jax.jit(
            functools.partial(merge, spec), donate_argnums=(0,)
        )

    def add(self, values, weights=None) -> "MomentDDSketch":
        """Ingest ``values[n_streams, S]`` (one fused dispatch); padding
        and NaN semantics match the dense tier.  Returns self."""
        _t0 = telemetry.clock() if telemetry._ACTIVE else None
        self._state = self._add(self._state, jnp.asarray(values), weights)
        if _t0 is not None:
            telemetry.finish_span(
                "ingest_s", _t0, component="moment", engine="moment"
            )
        from sketches_tpu import accuracy

        if accuracy._ACTIVE:
            accuracy.observe_ingest(self, values, weights)
        return self

    def get_quantile_value(self, q: float) -> np.ndarray:
        """Per-stream value at ``q`` -> ``[n_streams]`` (NaN if empty)."""
        return self.get_quantile_values([q])[:, 0]

    def get_quantile_values(self, quantiles: Sequence[float]) -> np.ndarray:
        """Maxent multi-quantile -> ``[n_streams, Q]`` (NaN for empty
        streams / out-of-range q; failed solves fall back, counted)."""
        return quantile(self.spec, self._state, [float(q) for q in quantiles])

    def get_quantile_values_resolved(
        self, quantiles: Sequence[float], disabled_tiers: Sequence[str] = (),
    ):
        """Serve-tier seam -> ``("moment", values)``.  The single
        engine ignores ``disabled_tiers`` (it is its own always-
        answerable floor); failures never tier-degrade -- the solver
        falls back internally instead."""
        return "moment", self.get_quantile_values(quantiles)

    def _query_choice(self, qs_tuple, extra_disabled=frozenset()):
        """Serve-tier seam: the resolved (tier, fn) pair -- always the
        single ``"moment"`` engine; exclusions are no-ops, never an
        error."""
        return (
            "moment",
            lambda state, qs_arr: quantile(
                self.spec, state, np.asarray(qs_arr)
            ),
        )

    def merge(self, other: "MomentDDSketch") -> "MomentDDSketch":
        """Fold ``other`` in (elementwise; consumes neither spec).
        Raises ``UnequalSketchParametersError`` on spec mismatch."""
        if not self.mergeable(other):
            from sketches_tpu.ddsketch import UnequalSketchParametersError

            raise UnequalSketchParametersError(
                "Cannot merge two moment sketches with different specs"
            )
        from sketches_tpu import integrity

        _fp_pre = None
        if integrity._ACTIVE:
            _fp_pre = integrity.fingerprint(
                self.spec, self._state
            ) + integrity.fingerprint(other.spec, other._state)
        self._state = self._merge(self._state, other._state)
        if _fp_pre is not None:
            integrity.verify_moment_merge(
                self.spec, self._state, _fp_pre, seam="moment.merge"
            )
        return self

    def mergeable(self, other) -> bool:
        return getattr(other, "spec", None) == self.spec

    @property
    def state(self) -> MomentState:
        return self._state

    @state.setter
    def state(self, new_state: MomentState) -> None:
        self._state = new_state

    @property
    def n_streams(self) -> int:
        return self._state.count.shape[0]

    @property
    def count(self) -> jax.Array:
        return self._state.count

    @property
    def sum(self) -> jax.Array:  # noqa: A003 - reference API name
        return self._state.sum

    @property
    def relative_accuracy(self) -> float:
        return self.spec.relative_accuracy

    def bytes_per_stream(self) -> int:
        """Device bytes per stream (~100 at the default k; never
        raises)."""
        return bytes_per_stream(self.spec)

    def __repr__(self) -> str:
        return (
            f"MomentDDSketch(n_streams={self.n_streams},"
            f" n_moments={self.spec.n_moments},"
            f" bytes_per_stream={self.bytes_per_stream()})"
        )
