"""UDDSketch-style uniform-collapse backend: alpha degrades, tails don't.

The dense device store clamps out-of-window keys into its edge bins:
mass is conserved but the tail quantiles silently corrupt, and the only
signal is the ``collapsed_low/high`` counters.  UDDSketch
(arXiv:2004.08604) replaces that failure mode with *uniform collapse*:
merge every adjacent bin pair, so the mapping's gamma squares
(``gamma -> gamma**2``), resolution halves EVERYWHERE, and the
relative-accuracy guarantee degrades predictably to

    alpha_eff(level) = (gamma**(2**level) - 1) / (gamma**(2**level) + 1)

instead of the tails becoming silently unbounded.

Level algebra (logarithmic mapping only -- enforced by ``SketchSpec``):
the base key of ``v`` is ``k0 = ceil(log_gamma v)`` and the level-L key
is ``ceil(k0 / 2**L)`` (``ceil(ceil(x)/m) == ceil(x/m)`` makes the
composition exact), so

* **ingest rides the batched/Pallas engines unchanged**: values for a
  collapsed stream are pre-mapped to the base-mapping representative of
  their level key (:func:`premap_values`, one tiny elementwise device
  op), after which the stock ingest scatters them into the right
  physical bin;
* **collapse is a pure state transform** (:func:`collapse_once`): bin
  mass at level key ``k`` scatters to ``ceil(k / 2)``, the per-stream
  window offset follows, and the per-stream ``level`` increments --
  mass exactly conserved, derived arrays recomputed from the rolled
  bins;
* **query post-corrects the decode** (:func:`correct_values`): the
  stock engines decode a level key ``k`` with the base mapping
  (``gamma**k * 2/(1+gamma)``); the level-true value is
  ``gamma_L**k * 2/(1+gamma_L)``, an exp of an affine function of
  ``k`` -- one elementwise op on the ``[n_streams, Q]`` result, riding
  whatever engine tier answered.

Merging mixed-gamma operands collapses the finer operand first
(:func:`collapse_to` to the pairwise max level), which commutes with
merge exactly (collapse is linear in the bins), and the armed integrity
layer fingerprints the *aligned* operands so the merge seam stays
fingerprint-accounted.

Failure modes: a collapse trigger (or explicit :meth:`collapse`) with
``SKETCHES_TPU_ADAPTIVE=0`` raises ``SpecError`` -- the kill switch
refuses loudly instead of degrading alpha silently; streams at
``spec.max_collapses`` stop collapsing and fall back to edge-clamping
(counted, as ever); quantiles of empty streams answer NaN; merging
unequal specs raises ``UnequalSketchParametersError``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sketches_tpu import batched, integrity, telemetry, tracing
from sketches_tpu.analysis import registry
from sketches_tpu.batched import BatchedDDSketch, SketchSpec, SketchState
from sketches_tpu.mapping import zero_threshold as mapping_zero_threshold
from sketches_tpu.resilience import SpecError

__all__ = [
    "AdaptiveState",
    "AdaptiveDDSketch",
    "init",
    "effective_gamma",
    "effective_alpha",
    "premap_values",
    "collapse_once",
    "collapse_to",
    "correct_values",
    "quantile",
    "merge",
    "psum_merge",
    "fold_hosts",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AdaptiveState:
    """Uniform-collapse device state: the dense base + per-stream level.

    ``base`` is a stock :class:`SketchState` whose bins hold mass at
    *level keys* (``ceil(base_key / 2**level)``); ``level`` is the
    per-stream collapse count (int32, 0 = base gamma).  Registered as a
    pytree, so it stacks/concats/psums exactly like the dense state.
    Empty streams answer NaN through :func:`quantile` like the dense
    tier; the pass-through properties keep collapse observability
    (``collapsed_low/high``) readable by the accuracy audit.
    """

    base: SketchState
    level: jax.Array  # [n_streams] int32

    @property
    def n_streams(self) -> int:
        return self.base.n_streams

    @property
    def count(self) -> jax.Array:
        return self.base.count

    @property
    def zero_count(self) -> jax.Array:
        return self.base.zero_count

    @property
    def collapsed_low(self) -> jax.Array:
        return self.base.collapsed_low

    @property
    def collapsed_high(self) -> jax.Array:
        return self.base.collapsed_high


def init(spec: SketchSpec, n_streams: int) -> AdaptiveState:
    """Empty adaptive batch: dense init + all-zero levels.  Raises
    ``SpecError`` (via the spec) for a non-logarithmic mapping."""
    return AdaptiveState(
        base=batched.init(spec, n_streams),
        level=jnp.zeros((n_streams,), jnp.int32),
    )


def effective_gamma(spec: SketchSpec, level) -> jax.Array:
    """Per-stream realized gamma: ``gamma ** (2 ** level)`` (f32)."""
    lng = jnp.float32(math.log(spec.gamma))
    return jnp.exp(jnp.exp2(jnp.asarray(level, jnp.float32)) * lng)


def effective_alpha(spec: SketchSpec, level) -> jax.Array:
    """Per-stream realized relative-accuracy bound.

    ``(g - 1) / (g + 1)`` with ``g = gamma ** (2 ** level)``: equals
    ``spec.relative_accuracy`` at level 0 and degrades toward (but
    never reaches) 1 as collapses accumulate.  Computed via ``tanh`` of
    the half-log for f32 stability at deep levels (where ``g``
    overflows f32 but alpha is just below 1).
    """
    lng = jnp.float32(math.log(spec.gamma))
    half = 0.5 * jnp.exp2(jnp.asarray(level, jnp.float32)) * lng
    return jnp.tanh(half)


def _ceil_div(k: jax.Array, m: jax.Array) -> jax.Array:
    """Elementwise ``ceil(k / m)`` for int32 ``k`` (any sign), ``m > 0``."""
    return -((-k) // m)


def premap_values(spec: SketchSpec, level: jax.Array, values) -> jax.Array:
    """Map raw values to base-mapping stand-ins for their level keys.

    For a stream at level L, the physical bins hold *level keys*
    ``ceil(base_key / 2**L)``; the stock ingest computes base keys, so
    each value is replaced by ``mapping.value(level_key)`` -- the base
    representative whose base key IS the level key (round-trip exact:
    the representative sits at the log-space midpoint of its bucket, so
    f32 rounding has ~0.5 bucket of margin).  Level-0 streams pass
    through untouched (bit-identical to the dense backend).  Zeros,
    NaNs, and subnormals pass through (they take the zero path / sum
    poisoning exactly as :func:`sketches_tpu.batched.add` documents);
    signs are preserved.  Note the collapsed streams' ``sum/min/max``
    bookkeeping then tracks the representatives -- within
    ``effective_alpha`` of the raw values, the documented contract.
    """
    v = jnp.asarray(values).astype(spec.dtype)
    if v.ndim == 1:
        v = v[:, None]
    lam = jnp.asarray(level, jnp.int32)[:, None]  # [N, 1]
    tiny = jnp.asarray(mapping_zero_threshold(v.dtype), v.dtype)
    absv = jnp.abs(v)
    routable = absv >= tiny  # NaN fails -> passes through untouched
    neutral = jnp.where(routable, absv, jnp.asarray(1.0, spec.dtype))
    k0 = spec.mapping.key_array(neutral)  # base keys [N, S]
    m = jnp.int32(1) << jnp.minimum(lam, 30)
    k_level = _ceil_div(k0, m)
    rep = spec.mapping.value_array(k_level, dtype=spec.dtype)
    u = jnp.where(
        jnp.logical_and(routable, lam > 0), jnp.sign(v) * rep, v
    )
    return u


def clamp_fraction(
    spec: SketchSpec, key_offset: jax.Array, level: jax.Array, values,
    weights=None,
) -> jax.Array:
    """Fraction of a batch's mass that would edge-clamp -> ``[n_streams]``.

    The pre-ingest collapse guard's predictor: the weighted fraction of
    live nonzero lanes whose level key falls outside the stream's
    current window.  Pure, jit-safe, one pass over the batch (no
    scatter); streams with no live nonzero lanes answer 0 (nothing can
    clamp).  NaN and padding lanes are excluded exactly like ingest.
    """
    v = jnp.asarray(values).astype(spec.dtype)
    if v.ndim == 1:
        v = v[:, None]
    if weights is None:
        w = jnp.ones_like(v)
    else:
        w = jnp.broadcast_to(jnp.asarray(weights, spec.dtype), v.shape)
    live = w > 0
    tiny = jnp.asarray(mapping_zero_threshold(v.dtype), v.dtype)
    absv = jnp.abs(v)
    routable = jnp.logical_and(live, absv >= tiny)
    neutral = jnp.where(routable, absv, jnp.asarray(1.0, spec.dtype))
    k0 = spec.mapping.key_array(neutral)
    m = jnp.int32(1) << jnp.minimum(
        jnp.asarray(level, jnp.int32)[:, None], 30
    )
    k_level = _ceil_div(k0, m)
    lo = jnp.asarray(key_offset, jnp.int32)[:, None]
    hi = lo + jnp.int32(spec.n_bins - 1)
    out = jnp.logical_and(
        routable, jnp.logical_or(k_level < lo, k_level > hi)
    )
    w_out = jnp.where(out, w, 0).sum(-1)
    w_all = jnp.where(routable, w, 0).sum(-1)
    return w_out / jnp.maximum(w_all, 1)


def level_auto_offset(
    spec: SketchSpec, level: jax.Array, key_offset: jax.Array, values,
    weights=None,
) -> jax.Array:
    """Window offsets centering each stream on a batch's median LEVEL key.

    The level-aware twin of :func:`sketches_tpu.batched.auto_offset`
    (same median-of-keys policy, same padding exclusions), used by the
    pre-ingest guard to ask "would a recenter at the CURRENT level fit
    this batch?" before paying a collapse for it.  Streams with no live
    nonzero values keep their current offset; pure and jit-safe.
    """
    v = jnp.asarray(values).astype(spec.dtype)
    if v.ndim == 1:
        v = v[:, None]
    tiny = jnp.asarray(mapping_zero_threshold(v.dtype), v.dtype)
    nonzero = jnp.abs(v) >= tiny  # NaN fails -> excluded
    if weights is not None:
        w = jnp.broadcast_to(jnp.asarray(weights, spec.dtype), v.shape)
        nonzero = jnp.logical_and(nonzero, w > 0)
    absv = jnp.where(nonzero, jnp.abs(v), jnp.asarray(1.0, spec.dtype))
    k0 = spec.mapping.key_array(absv)
    m = jnp.int32(1) << jnp.minimum(
        jnp.asarray(level, jnp.int32)[:, None], 30
    )
    keys = _ceil_div(k0, m)
    big = jnp.int32(2**30)
    ksort = jnp.sort(jnp.where(nonzero, keys, big), axis=-1)
    n_live = nonzero.sum(-1)
    mid = jnp.maximum((n_live - 1) // 2, 0)
    med = jnp.take_along_axis(
        ksort, mid[:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    centered = med - jnp.int32(batched._center_bin(spec))
    return jnp.where(
        n_live > 0, centered, jnp.asarray(key_offset, jnp.int32)
    ).astype(jnp.int32)


def _collapse_body(spec: SketchSpec, state: SketchState, mask: jax.Array):
    """One uniform collapse of the masked streams' bins (pure, device).

    Level key ``k`` scatters to ``ceil(k / 2)``; the window offset
    follows (``ceil(key_offset / 2)``), so post-collapse occupancy sits
    in the lower half of the window -- the freed headroom is the
    mechanism that ends an edge-clamping episode.  Unmasked rows are
    bit-identical pass-throughs.  Mass is exactly conserved (the
    scatter moves every bin); derived arrays (occupied bounds, tile
    sums) recompute from the rolled bins.
    """
    n_bins = spec.n_bins
    koff = state.key_offset  # [N] level keys' low edge
    new_koff = jnp.where(mask, _ceil_div(koff, jnp.int32(2)), koff)
    iota = jnp.arange(n_bins, dtype=jnp.int32)
    old_key = koff[:, None] + iota[None, :]  # [N, B]
    tgt = _ceil_div(old_key, jnp.int32(2)) - new_koff[:, None]
    idx = jnp.where(
        mask[:, None], jnp.clip(tgt, 0, n_bins - 1), iota[None, :]
    )

    def _roll_row(bins_row, idx_row):
        return jnp.zeros_like(bins_row).at[idx_row].add(bins_row)

    roll = jax.vmap(_roll_row)
    new_pos = roll(state.bins_pos, idx)
    new_neg = roll(state.bins_neg, idx)
    pos_lo, pos_hi = batched._occupied_bounds(new_pos)
    neg_lo, neg_hi = batched._occupied_bounds(new_neg)
    return dataclasses.replace(
        state,
        bins_pos=new_pos,
        bins_neg=new_neg,
        key_offset=new_koff,
        pos_lo=pos_lo,
        pos_hi=pos_hi,
        neg_lo=neg_lo,
        neg_hi=neg_hi,
        tile_sums=batched.tile_sums_of(new_pos, new_neg),
    )


def collapse_once(
    spec: SketchSpec, astate: AdaptiveState, mask=None
) -> AdaptiveState:
    """Collapse the masked streams one level (gamma -> gamma**2).

    ``mask`` is a ``[n_streams]`` bool (default: all streams); streams
    already at ``spec.max_collapses`` are excluded -- they keep their
    level and fall back to edge-clamping (counted by the collapse
    counters as ever).  Pure function, jit-safe; mass exactly conserved.
    """
    if mask is None:
        mask = jnp.ones((astate.n_streams,), bool)
    mask = jnp.logical_and(
        jnp.asarray(mask, bool), astate.level < spec.max_collapses
    )
    return AdaptiveState(
        base=_collapse_body(spec, astate.base, mask),
        level=astate.level + mask.astype(jnp.int32),
    )


def collapse_to(
    spec: SketchSpec, astate: AdaptiveState, target_level
) -> AdaptiveState:
    """Collapse each stream up to ``target_level`` (scalar or [N]).

    Streams already at or past their target are untouched (levels never
    decrease -- resolution, once lost, is lost).  Unrolls
    ``spec.max_collapses`` single collapses (jit-safe static bound), so
    keep ``max_collapses`` modest.  Mass exactly conserved.
    """
    target = jnp.broadcast_to(
        jnp.asarray(target_level, jnp.int32), astate.level.shape
    )
    for _ in range(spec.max_collapses):
        astate = collapse_once(spec, astate, astate.level < target)
    return astate


def correct_values(spec: SketchSpec, level: jax.Array, vals) -> jax.Array:
    """Re-decode base-mapping query output at each stream's true level.

    The stock engines answer ``gamma**k * 2/(1+gamma)`` for a chosen
    level key ``k``; the level-true representative is
    ``gamma_L**k * 2/(1+gamma_L)``.  The key is recovered exactly from
    the base decode (it sits mid-bucket in log space) and the corrected
    value is computed as one fused ``exp`` of an affine function of
    ``k`` -- overflow-safe via ``logaddexp`` (saturating like
    ``value_array``; quantiles stay finite).  Level-0 rows, zeros, and
    NaNs pass through bit-identically.
    """
    v = jnp.asarray(vals)
    lam = jnp.asarray(level, jnp.int32)
    lam = lam.reshape(lam.shape + (1,) * (v.ndim - 1))  # [N, 1...] vs [N, Q]
    tiny = jnp.asarray(mapping_zero_threshold(v.dtype), v.dtype)
    absv = jnp.abs(v)
    routable = absv >= tiny  # NaN fails -> untouched
    neutral = jnp.where(routable, absv, jnp.asarray(1.0, v.dtype))
    k = spec.mapping.key_array(neutral).astype(jnp.float32)  # level key
    m = jnp.exp2(jnp.minimum(lam, 64).astype(jnp.float32))
    lng = jnp.float32(math.log(spec.gamma))
    # log of gamma_L**k * 2/(1+gamma_L)  =  k*m*ln(g) + ln2 - log1p(g**m)
    log_out = (
        k * m * lng
        + jnp.float32(math.log(2.0))
        - jnp.logaddexp(jnp.float32(0.0), m * lng)
    )
    fin = jnp.finfo(v.dtype)
    corrected = jnp.clip(
        jnp.exp(log_out),
        jnp.asarray(fin.tiny, v.dtype),
        jnp.asarray(fin.max, v.dtype),
    ).astype(v.dtype)
    return jnp.where(
        jnp.logical_and(routable, lam > 0),
        jnp.sign(v) * corrected,
        v,
    )


def quantile(spec: SketchSpec, astate: AdaptiveState, qs) -> jax.Array:
    """Level-corrected fused multi-quantile -> ``[n_streams, Q]``.

    The dense rank selection runs unchanged on the base state; the
    decode is then re-done at each stream's level
    (:func:`correct_values`).  Answers are within
    ``effective_alpha(spec, level)`` of the true quantiles; empty
    streams and out-of-range q answer NaN exactly like the dense tier.
    """
    return correct_values(
        spec, astate.level, batched.quantile(spec, astate.base, qs)
    )


def _union_span(spec: SketchSpec, sa: SketchState, sb: SketchState):
    """Combined occupied absolute-key bounds of two bases ->
    ``(lo [N], hi [N], occupied [N])`` (sentinel-safe; empty pairs
    report ``occupied=False``)."""
    big = jnp.int32(2**30)

    def _bounds(st):
        has = st.occ_hi >= 0
        lo = jnp.where(has, st.key_offset + st.occ_lo, big)
        hi = jnp.where(has, st.key_offset + st.occ_hi, -big)
        return lo, hi

    la, ha = _bounds(sa)
    lb, hb = _bounds(sb)
    lo = jnp.minimum(la, lb)
    hi = jnp.maximum(ha, hb)
    occupied = jnp.logical_or(sa.occ_hi >= 0, sb.occ_hi >= 0)
    return lo, hi, occupied


def align_for_merge(
    spec: SketchSpec, a: AdaptiveState, b: AdaptiveState
):
    """Bring two operands onto one (level, window) per stream ->
    ``(a', b')`` ready for an elementwise merge.

    Three mass-conserving steps, all pure: (1) the finer operand
    collapses to the pairwise max level; (2) while the operands'
    combined occupied span cannot fit one window, BOTH collapse further
    (``gamma -> gamma**2`` beats folding disjoint regimes into edge
    bins -- the whole point of the backend); streams at
    ``spec.max_collapses`` stop and will fold (counted); (3) both
    recenter onto a shared union-centered window.  Levels in the
    result are equal by construction.
    """
    target = jnp.maximum(a.level, b.level)
    a = collapse_to(spec, a, target)
    b = collapse_to(spec, b, target)
    for _ in range(spec.max_collapses):
        lo, hi, occupied = _union_span(spec, a.base, b.base)
        span = hi - lo + 1
        need = jnp.logical_and(
            jnp.logical_and(occupied, span > spec.n_bins),
            a.level < spec.max_collapses,
        )
        a = collapse_once(spec, a, need)
        b = collapse_once(spec, b, need)
    lo, hi, occupied = _union_span(spec, a.base, b.base)
    span = jnp.clip(hi - lo + 1, 0, spec.n_bins)
    koff_t = jnp.where(
        occupied, lo - (spec.n_bins - span) // 2, a.base.key_offset
    ).astype(jnp.int32)
    return (
        AdaptiveState(batched.recenter(spec, a.base, koff_t), a.level),
        AdaptiveState(batched.recenter(spec, b.base, koff_t), b.level),
    )


def merge(
    spec: SketchSpec, a: AdaptiveState, b: AdaptiveState
) -> AdaptiveState:
    """Merge mixed-gamma operands: collapse the finer one first.

    Per stream, both operands align through :func:`align_for_merge`
    (max level, widened until the union fits, shared window), then the
    bases merge elementwise.  Collapse commutes with merge (it is
    linear in the bins), so this equals collapsing AFTER the merge --
    the reference semantics the tests pin.  Mass exactly conserved;
    pure function; streams at the level cap fold at the edges
    (counted) rather than failing.
    """
    a2, b2 = align_for_merge(spec, a, b)
    return AdaptiveState(
        base=batched.merge_aligned(spec, a2.base, b2.base),
        level=a2.level,
    )


def psum_merge(spec: SketchSpec, astate: AdaptiveState, axis_name):
    """Collective fold of adaptive partials over mesh axes.

    Must run inside ``shard_map``/pmap.  Levels align first (``pmax``
    over the axes, then :func:`collapse_to` locally -- the finer
    operands collapse before any mass crosses the interconnect), then
    the bases fold through the stock hierarchical
    :func:`sketches_tpu.parallel.psum_merge`.  Requires the distributed
    tier's usual discipline (shared init; partials never recentered
    independently); all-dead axes raise at the caller as ever.
    """
    from sketches_tpu.parallel import _pmax_axes, _value_axes
    from sketches_tpu.parallel import psum_merge as _base_psum

    axes = _value_axes(axis_name)
    target = _pmax_axes(astate.level, axes)
    aligned = collapse_to(spec, astate, target)
    return AdaptiveState(
        base=_base_psum(aligned.base, axis_name), level=target
    )


def fold_hosts(spec: SketchSpec, astates: Sequence[AdaptiveState],
               reachable=None):
    """Cross-host fold of adaptive per-host partials ->
    ``(folded AdaptiveState, ShardLossReport)``.

    Levels align to the elementwise max over *reachable* hosts (an
    unreachable host's finer/coarser level must not force survivors to
    collapse), then the aligned bases fold through the stock
    :func:`sketches_tpu.parallel.fold_hosts` -- same
    fingerprint-verified lane, same partition accounting; no host
    reachable raises ``ShardLossError`` and an empty/mismatched stack
    raises ``SketchValueError`` exactly as the dense fold does.
    """
    from sketches_tpu import parallel

    n_hosts = len(astates)
    reach = None
    if reachable is not None:
        reach = np.asarray(reachable, bool).reshape(-1)
    levels = np.stack(
        [np.asarray(jax.device_get(st.level)) for st in astates]
    )
    live = reach if reach is not None else np.ones((n_hosts,), bool)
    if n_hosts and live.shape[0] == n_hosts and live.any():
        target = levels[live].max(0)
    else:
        target = levels.max(0) if n_hosts else levels
    aligned = [
        collapse_to(spec, st, jnp.asarray(target)) for st in astates
    ]
    folded_base, report = parallel.fold_hosts(
        spec, [st.base for st in aligned], reachable=reachable
    )
    return (
        AdaptiveState(base=folded_base, level=jnp.asarray(target)),
        report,
    )


class AdaptiveDDSketch:
    """Stateful facade for the uniform-collapse backend.

    Wraps a stock :class:`BatchedDDSketch` (the engines -- Pallas
    ingest, the overlap/tiles/windowed/xla query ladder, the health
    ladder -- all ride unchanged) and adds the level machinery: ingest
    premaps values for collapsed streams, the collapse trigger fires
    when a stream's *recent* edge-clamped mass fraction crosses
    ``spec.collapse_threshold``, and queries post-correct the decode.

    Failure modes: a firing trigger (or explicit :meth:`collapse`) with
    ``SKETCHES_TPU_ADAPTIVE=0`` raises ``SpecError`` (the kill switch
    refuses loudly); streams at ``spec.max_collapses`` stop collapsing
    and clamp at the edges (counted); merging unequal specs raises
    ``UnequalSketchParametersError``; empty streams answer NaN; the
    wrapped engine ladder degrades/raises exactly as the dense facade
    documents.
    """

    def __init__(
        self,
        n_streams: int,
        relative_accuracy: float = batched.DEFAULT_REL_ACC,
        n_bins: int = batched.DEFAULT_N_BINS,
        key_offset: Optional[int] = None,
        spec: Optional[SketchSpec] = None,
        state: Optional[AdaptiveState] = None,
        engine: str = "auto",
        auto_recenter: Optional[bool] = None,
        bin_dtype=None,
        collapse_threshold: Optional[float] = None,
    ):
        if spec is None:
            spec = SketchSpec(
                relative_accuracy=relative_accuracy,
                mapping_name="logarithmic",
                n_bins=n_bins,
                key_offset=key_offset,
                bin_dtype=bin_dtype,
                backend="uniform_collapse",
                collapse_threshold=(
                    0.01 if collapse_threshold is None else collapse_threshold
                ),
            )
        if spec.backend != "uniform_collapse":
            raise SpecError(
                f"AdaptiveDDSketch needs backend='uniform_collapse';"
                f" got {spec.backend!r}"
            )
        self.spec = spec
        if auto_recenter is None:
            # The batched facade treats an explicit spec as a pinned
            # window; the adaptive facade ALWAYS carries a spec, so the
            # equivalent default is "auto-center unless the caller
            # pinned the window or restored a state" -- an off-center
            # window clamps, and clamping is what this backend spends
            # alpha to avoid.
            auto_recenter = key_offset is None and state is None
        self._inner = BatchedDDSketch(
            n_streams,
            spec=spec,
            state=None if state is None else state.base,
            engine=engine,
            auto_recenter=auto_recenter,
        )
        self._level = (
            jnp.zeros((n_streams,), jnp.int32)
            if state is None
            else jnp.asarray(state.level, jnp.int32)
        )
        # Host-cached "any stream collapsed yet" flag: the ingest premap
        # is an exact no-op at level 0, so fresh facades skip it without
        # a per-add device fetch.
        self._any_level = state is not None and bool(
            np.any(np.asarray(jax.device_get(self._level)) > 0)
        )
        # Trigger baseline: edge-clamp counters at the last collapse (or
        # construction) -- the trigger compares *growth* since then, so
        # one clamped episode cannot keep re-firing forever.
        self._trigger_collapsed = np.asarray(
            jax.device_get(
                self._inner.state.collapsed_low
                + self._inner.state.collapsed_high
            ),
            np.float64,
        )
        self._premap = jax.jit(functools.partial(premap_values, spec))
        self._clamp_frac = jax.jit(functools.partial(clamp_fraction, spec))
        self._level_offs = jax.jit(
            functools.partial(level_auto_offset, spec)
        )

        def _guard_stats(koff, level, values, weights):
            # One fused device pass for the pre-ingest guard: clamp
            # fraction vs the CURRENT window, the batch-median-centered
            # offsets, and the clamp fraction vs THAT window.
            frac_now = clamp_fraction(spec, koff, level, values, weights)
            offs = level_auto_offset(spec, level, koff, values, weights)
            frac_ctr = clamp_fraction(spec, offs, level, values, weights)
            return frac_now, offs, frac_ctr

        self._guard_stats = jax.jit(_guard_stats)

        def _collapse_and_center(astate, mask):
            # Collapse, then recenter the collapsed streams onto their
            # binned-mass median: ceil(key_offset / 2) alone leaves the
            # halved occupancy off-center, and an off-center window
            # keeps clamping (and keeps collapsing) on data a centered
            # window would hold.
            new = collapse_once(spec, astate, mask)
            did = new.level > astate.level
            offs = batched.data_center_offsets(spec, new.base)
            base = batched.recenter(
                spec, new.base,
                jnp.where(did, offs, new.base.key_offset),
            )
            return AdaptiveState(base, new.level)

        self._collapse_center = jax.jit(_collapse_and_center)
        self._correct = jax.jit(functools.partial(correct_values, spec))
        self._collapse_once = jax.jit(
            functools.partial(collapse_once, spec)
        )
        self._collapse_to = jax.jit(functools.partial(collapse_to, spec))
        self._align_merge = jax.jit(functools.partial(align_for_merge, spec))

    # -- core API ----------------------------------------------------------
    def add(self, values, weights=None) -> "AdaptiveDDSketch":
        """Ingest ``values[n_streams, S]``; returns self for chaining.

        Two collapse triggers guard the batch:

        * **pre-ingest guard** -- the batch's predicted edge-clamp
          fraction at the current level (streams that already hold
          binned mass only; empty streams auto-center first).  Streams
          over ``spec.collapse_threshold`` collapse BEFORE the scatter,
          so predictable clamping never loses resolution -- the
          UDDSketch no-loss behavior;
        * **post-ingest counter trigger** -- growth of the
          ``collapsed_mass_frac`` counters past the threshold (the
          backstop for mass that clamped anyway, e.g. a fresh stream's
          very first batch outrunning its level-0 window; such a
          stream stabilizes within a collapse or two).

        Collapsed streams' values premap to their level representatives
        (one elementwise device op), then the stock engines ingest.
        Padding (``weights <= 0``), NaN, and empty-batch semantics
        match :meth:`BatchedDDSketch.add` exactly.  Raises ``SpecError``
        when a trigger fires while ``SKETCHES_TPU_ADAPTIVE=0``.
        """
        varr = jnp.asarray(values)
        self._preguard(varr, weights)
        v = varr if not self._any_level else self._premap(self._level, varr)
        self._inner.add(v, weights)
        self._maybe_collapse()
        return self

    def _preguard(self, varr, weights) -> None:
        """Pre-ingest collapse guard (see :meth:`add`).

        Per over-threshold stream, the cheaper fix wins: if a window
        RECENTER at the current level would fit the batch (the clamp is
        a regime *shift*), the window slides -- no alpha loss; only
        when even a centered window cannot hold the batch (the clamp is
        *width*) does the stream collapse.  Raises ``SpecError`` when a
        collapse is needed while the kill switch is 0 (recentering
        alone stays allowed -- it is the dense tier's own mechanism).
        """
        st = self._inner.state
        has_mass = (
            np.asarray(jax.device_get(st.count - st.zero_count), np.float64)
            > 0
        )
        thr = self.spec.collapse_threshold
        for _ in range(self.spec.max_collapses + 2):
            st = self._inner.state
            frac_now_d, offs, frac_ctr_d = self._guard_stats(
                st.key_offset, self._level, varr, weights
            )
            frac_now = np.asarray(jax.device_get(frac_now_d), np.float64)
            frac_centered = np.asarray(jax.device_get(frac_ctr_d), np.float64)
            level = np.asarray(jax.device_get(self._level))
            # Empty streams judge against the window their first batch
            # will auto-center (their current offset is provisional);
            # occupied streams judge against the window they have.
            relevant = np.where(has_mass, frac_now, frac_centered)
            bad = relevant > thr
            if not bad.any():
                return
            collapse_mask = (
                bad & (frac_centered > thr)
                & (level < self.spec.max_collapses)
            )
            recenter_mask = bad & has_mass & (frac_centered <= thr)
            if collapse_mask.any():
                if not registry.enabled(registry.ADAPTIVE):
                    raise SpecError(
                        "pre-ingest uniform collapse triggered on"
                        f" streams {np.nonzero(collapse_mask)[0].tolist()[:8]}"
                        " but SKETCHES_TPU_ADAPTIVE=0: refusing to"
                        " degrade alpha (widen the window or re-enable"
                        " the switch)"
                    )
                self._apply_collapse(np.asarray(collapse_mask))
            elif recenter_mask.any():
                self._inner.recenter(
                    jnp.where(
                        jnp.asarray(recenter_mask), offs, st.key_offset
                    )
                )
            else:
                return  # only at-cap streams remain: they clamp, counted

    def _maybe_collapse(self) -> bool:
        """Run the collapse trigger -> whether any stream collapsed.

        A stream triggers when the growth of its edge-clamped mass
        since the last collapse exceeds ``spec.collapse_threshold``
        of its binned mass.  Raises ``SpecError`` when the trigger
        fires while the ``SKETCHES_TPU_ADAPTIVE`` kill switch is 0
        (refuse loudly: silent alpha degradation is exactly what the
        switch exists to forbid).
        """
        st = self._inner.state
        collapsed, binned, level = (
            np.asarray(a, np.float64)
            for a in jax.device_get(
                (
                    st.collapsed_low + st.collapsed_high,
                    st.count - st.zero_count,
                    self._level,
                )
            )
        )
        growth = collapsed - self._trigger_collapsed
        mask = (growth > self.spec.collapse_threshold * np.maximum(binned, 1.0)) & (
            level < self.spec.max_collapses
        )
        if not mask.any():
            return False
        if not registry.enabled(registry.ADAPTIVE):
            raise SpecError(
                "uniform collapse triggered on streams"
                f" {np.nonzero(mask)[0].tolist()[:8]} but"
                " SKETCHES_TPU_ADAPTIVE=0: refusing to degrade alpha"
                " (raise the window, recenter, or re-enable the switch)"
            )
        self._apply_collapse(np.asarray(mask))
        return True

    def _apply_collapse(self, mask: np.ndarray) -> None:
        astate = self._collapse_center(
            AdaptiveState(self._inner.state, self._level), jnp.asarray(mask)
        )
        self._inner.state = astate.base  # setter: plans + policy reset
        self._level = astate.level
        self._any_level = True
        st = self._inner.state
        self._trigger_collapsed = np.asarray(
            jax.device_get(st.collapsed_low + st.collapsed_high), np.float64
        )
        n = int(mask.sum())
        if telemetry._ACTIVE:
            telemetry.counter_inc("backend.collapses", float(n))
            alpha = np.asarray(
                jax.device_get(effective_alpha(self.spec, self._level))
            )
            for s in np.nonzero(mask)[0][:8]:
                telemetry.gauge_set(
                    "backend.effective_alpha", float(alpha[s]),
                    stream=int(s),
                )
        if tracing._ACTIVE:
            tracing.record_event(
                "backend.collapse", n_streams=n, component="adaptive"
            )

    def collapse(self, mask=None) -> "AdaptiveDDSketch":
        """Collapse the masked streams one level explicitly.

        Same kill-switch contract as the automatic trigger: raises
        ``SpecError`` when ``SKETCHES_TPU_ADAPTIVE=0``.  Streams at
        ``spec.max_collapses`` are silently excluded (they can only
        clamp).  Returns self.
        """
        if not registry.enabled(registry.ADAPTIVE):
            raise SpecError(
                "explicit collapse refused: SKETCHES_TPU_ADAPTIVE=0"
            )
        m = (
            np.ones((self.n_streams,), bool)
            if mask is None
            else np.asarray(mask, bool)
        )
        self._apply_collapse(m)
        return self

    def get_quantile_value(self, q: float) -> jax.Array:
        """Per-stream value at ``q`` -> ``[n_streams]`` (NaN if empty)."""
        return self.get_quantile_values([q])[:, 0]

    def get_quantile_values(self, quantiles: Sequence[float]) -> jax.Array:
        """Level-corrected fused multi-quantile -> ``[n_streams, Q]``.

        Within ``effective_alpha()`` of the true quantiles per stream
        (the degraded-but-declared contract); NaN for empty streams or
        out-of-range q; engine failures degrade down the wrapped
        ladder exactly like the dense facade.
        """
        return self._correct(
            self._level, self._inner.get_quantile_values(quantiles)
        )

    def get_quantile_values_resolved(
        self, quantiles: Sequence[float], disabled_tiers: Sequence[str] = (),
    ):
        """:meth:`get_quantile_values` that also names the engine tier
        -> ``(tier, [n_streams, Q])``; tier exclusions and failure
        degradation ride the wrapped facade unchanged."""
        tier, vals = self._inner.get_quantile_values_resolved(
            quantiles, disabled_tiers=disabled_tiers
        )
        return tier, self._correct(self._level, vals)

    def _query_choice(self, qs_tuple, extra_disabled=frozenset()):
        """Serve-tier seam: the wrapped facade's resolved tier/fn (the
        correction rides :meth:`get_quantile_values_resolved`; failures
        degrade identically)."""
        return self._inner._query_choice(qs_tuple, extra_disabled)

    def merge(self, other: "AdaptiveDDSketch") -> "AdaptiveDDSketch":
        """Fold ``other`` in, collapsing the finer operand first.

        Mixed-gamma merge: per stream both operands collapse to the
        pairwise max level, then the bases merge window-aligned with
        the armed integrity layer fingerprinting the ALIGNED operands
        (fingerprint-accounted; collapse legitimately changes content,
        so accounting happens after alignment).  Raises
        ``UnequalSketchParametersError`` on spec mismatch.
        """
        if not self.mergeable(other):
            from sketches_tpu.ddsketch import UnequalSketchParametersError

            raise UnequalSketchParametersError(
                "Cannot merge two adaptive sketches with different specs"
            )
        mine, theirs = self._align_merge(
            AdaptiveState(self._inner.state, self._level),
            AdaptiveState(other._inner.state, other._level),
        )
        target = mine.level
        if not registry.enabled(registry.ADAPTIVE):
            # Alignment is pure, so nothing has committed yet: refuse
            # the merge loudly if it would have collapsed either side.
            deepened = np.asarray(
                jax.device_get(
                    jnp.logical_or(
                        target > self._level, target > other._level
                    )
                )
            )
            if deepened.any():
                raise SpecError(
                    "mixed-gamma merge needs a collapse on streams"
                    f" {np.nonzero(deepened)[0].tolist()[:8]} but"
                    " SKETCHES_TPU_ADAPTIVE=0: refusing to degrade"
                    " alpha"
                )
        _ipre = (
            integrity.premerge(self.spec, mine.base, theirs.base)
            if integrity._ACTIVE
            else None
        )
        self._inner.state = mine.base
        self._inner._stream_op(
            "merge_aligned", self._inner._merge_body, theirs.base
        )
        self._inner._invalidate_plans()
        self._level = target
        self._any_level = self._any_level or other._any_level or bool(
            np.any(np.asarray(jax.device_get(target)) > 0)
        )
        if _ipre is not None:
            integrity.postmerge(
                self.spec, self._inner.state, _ipre, seam="adaptive.merge"
            )
        self._trigger_collapsed = np.asarray(
            jax.device_get(
                self._inner.state.collapsed_low
                + self._inner.state.collapsed_high
            ),
            np.float64,
        )
        return self

    def mergeable(self, other) -> bool:
        return getattr(other, "spec", None) == self.spec

    # -- observability -----------------------------------------------------
    def effective_alpha(self) -> jax.Array:
        """Per-stream realized relative-accuracy bound -> ``[n_streams]``
        (``spec.relative_accuracy`` until a stream collapses; the
        quantile error contract every answer satisfies)."""
        return effective_alpha(self.spec, self._level)

    def collapsed_fraction(self) -> jax.Array:
        """Per-stream edge-clamped mass fraction (host sync; see
        :meth:`BatchedDDSketch.collapsed_fraction`)."""
        return self._inner.collapsed_fraction()

    @property
    def level(self) -> jax.Array:
        return self._level

    @property
    def state(self) -> AdaptiveState:
        return AdaptiveState(self._inner.state, self._level)

    @state.setter
    def state(self, new_state: AdaptiveState) -> None:
        # External choke point (checkpoint restore): inner caches reset
        # via the wrapped setter; the trigger re-baselines (comparing
        # growth against another state's history would misfire).
        self._inner.state = new_state.base
        self._level = jnp.asarray(new_state.level, jnp.int32)
        self._any_level = bool(
            np.any(np.asarray(jax.device_get(self._level)) > 0)
        )
        self._trigger_collapsed = np.asarray(
            jax.device_get(
                new_state.base.collapsed_low + new_state.base.collapsed_high
            ),
            np.float64,
        )

    @property
    def n_streams(self) -> int:
        return self._inner.n_streams

    @property
    def count(self) -> jax.Array:
        return self._inner.count

    @property
    def relative_accuracy(self) -> float:
        return self.spec.relative_accuracy

    def __repr__(self) -> str:
        return (
            f"AdaptiveDDSketch(n_streams={self.n_streams},"
            f" n_bins={self.spec.n_bins},"
            f" relative_accuracy={self.spec.relative_accuracy},"
            f" threshold={self.spec.collapse_threshold})"
        )
