"""Adaptive-accuracy device backends behind the Store/KeyMapping seam.

Every tenant used to pay one accuracy/memory contract: a dense
``[n_streams, n_bins]`` bin store at a fixed alpha, with out-of-window
mass silently clamped into the edge bins (counted by
``collapsed_low/high``, but resolution, once lost, was lost).  This
package opens the frontier to three contracts, selected per
``SketchSpec.backend``:

* ``"dense"`` -- the classic store (``sketches_tpu.batched``); nothing
  here changes it.
* ``"uniform_collapse"`` -- UDDSketch-style graceful degradation
  (arXiv:2004.08604): when a stream's edge-clamped mass fraction
  crosses ``spec.collapse_threshold``, adjacent bin pairs merge
  uniformly (gamma -> gamma**2), halving resolution everywhere instead
  of corrupting the tails; the per-stream collapse level rides in
  :class:`~sketches_tpu.backends.uniform.AdaptiveState` and the
  realized guarantee is ``effective_alpha``.  See
  :mod:`sketches_tpu.backends.uniform`.
* ``"moment"`` -- a compact moment summary (arXiv:1803.01969):
  ~``2 * n_moments + 6`` f32 scalars per stream (~100 bytes at the
  default k=12, vs ~4 KiB for 512 f32 bins), batched ingest fused into
  one device dispatch, quantiles estimated on the host by a
  maximum-entropy solve.  See :mod:`sketches_tpu.backends.moment`.

Failure modes: :func:`facade_for` raises ``SpecError`` for an unknown
backend name; the uniform-collapse trigger raises ``SpecError`` when
``SKETCHES_TPU_ADAPTIVE=0`` (the kill switch -- collapse refuses
loudly rather than degrading alpha behind an operator's back); moment
quantiles fall back down a documented solver ladder and answer NaN
only for empty streams.
"""

from __future__ import annotations

from sketches_tpu.resilience import SpecError

__all__ = [
    "BACKEND_DENSE",
    "BACKEND_UNIFORM_COLLAPSE",
    "BACKEND_MOMENT",
    "BACKEND_WINDOWED",
    "BACKEND_ENUM",
    "BACKEND_NAMES",
    "facade_for",
]

#: Wire-enum values (``SketchPayload.backend``; see
#: ``sketches_tpu.backends.wirefmt``).  Append-only: decoders refuse
#: unknown values loudly, so reusing a retired number would silently
#: misdecode old blobs.  ``BACKEND_WINDOWED`` is an *envelope-only*
#: kind (a whole ring of bucket sketches, not a ``SketchSpec.backend``
#: value): pre-r18 readers refuse it by enum value, and r18+ readers
#: under a plain backend spec refuse it by name -- either way loudly.
BACKEND_DENSE = 0
BACKEND_UNIFORM_COLLAPSE = 1
BACKEND_MOMENT = 2
BACKEND_WINDOWED = 3

#: backend name -> wire enum value (the ONE place the mapping lives).
BACKEND_ENUM = {
    "dense": BACKEND_DENSE,
    "uniform_collapse": BACKEND_UNIFORM_COLLAPSE,
    "moment": BACKEND_MOMENT,
    "windowed": BACKEND_WINDOWED,
}

#: wire enum value -> backend name.
BACKEND_NAMES = {v: k for k, v in BACKEND_ENUM.items()}


def facade_for(n_streams: int, **kwargs):
    """Construct the facade matching ``kwargs``' spec/backend.

    The single constructor seam the serving tier (and any other
    spec-driven caller) uses: ``spec.backend`` picks the class --
    ``BatchedDDSketch`` (dense), ``AdaptiveDDSketch``
    (uniform_collapse), or ``MomentDDSketch`` (moment).  A ``backend=``
    keyword is also accepted in place of a full spec.  Raises
    ``SpecError`` for an unknown backend name (via ``SketchSpec``
    validation); all other kwargs pass through to the facade.
    """
    spec = kwargs.get("spec")
    backend = kwargs.pop("backend", None)
    if backend is None:
        backend = getattr(spec, "backend", "dense")
    elif spec is not None and spec.backend != backend:
        raise SpecError(
            f"backend={backend!r} contradicts spec.backend="
            f"{spec.backend!r}"
        )
    if backend == "uniform_collapse":
        from sketches_tpu.backends.uniform import AdaptiveDDSketch

        return AdaptiveDDSketch(n_streams, **kwargs)
    if backend == "moment":
        from sketches_tpu.backends.moment import MomentDDSketch

        return MomentDDSketch(n_streams, **kwargs)
    if backend != "dense":
        raise SpecError(f"Unknown backend {backend!r}")
    from sketches_tpu.batched import BatchedDDSketch

    return BatchedDDSketch(n_streams, **kwargs)
