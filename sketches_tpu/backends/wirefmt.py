"""Backend-tagged wire envelope: the ``SketchPayload`` message.

The upstream DDSketch protobuf (``sketches_tpu.pb``) has no slot for a
backend kind, a collapse level, or a moment vector -- and its first
byte is always ``0x0a`` (field 1, the length-delimited ``mapping``
submessage).  The envelope exploits that: a ``SketchPayload`` starts
with field 1 as a *varint* (``0x08``), so the two formats are
distinguishable from the first byte and plain dense blobs stay
byte-identical to the classic path (full interop compatibility).

Hand-rolled proto3 wire encoding, the ``pb/wire.py`` discipline::

    message SketchPayload {
      enum Backend { DENSE = 0; UNIFORM_COLLAPSE = 1; MOMENT = 2;
                     WINDOWED = 3; }
      Backend backend = 1;          // varint, always emitted
      bytes   dense   = 2;          // classic DDSketch blob (dense/collapse)
      uint32  level   = 3;          // uniform_collapse: stream's level
      bytes   moment  = 4;          // MomentPayload submessage
      bytes   windowed = 5;         // WindowedPayload submessage (r18)
    }
    message MomentPayload {
      uint32 k        = 1;          // number of power sums per basis
      // packed doubles: [count, zero_count, neg_count, sum, min, max]
      repeated double scalars      = 2;
      repeated double powers       = 3;  // k raw power sums
      repeated double log_powers   = 4;  // k log power sums
    }
    message WindowedPayload {       // a whole ring, ONE blob
      uint32 n_streams             = 1;
      repeated double slices_s     = 2;  // packed; ladder rung widths
      repeated double lengths      = 3;  // packed; ring lengths per rung
      repeated double ledger       = 4;  // packed: [total, retired,
                                         //   rotations, ladder_collapses]
      repeated double collapse_levels = 5;  // packed; absent = none
      repeated BucketEntry buckets = 6;
      uint64 cur_plus1             = 7;  // 0 = no current slice yet
    }
    message BucketEntry {
      uint32 rung     = 1;
      uint64 id       = 2;          // bucket index = floor(t / slice)
      repeated double mass = 3;     // packed, one exact ledger entry
      uint32 live     = 4;          // 1 = the ring's current bucket
      repeated bytes stream = 5;    // one inner payload blob per stream
    }

Forward compatibility is LOUD by design: a decoder that meets an
unknown ``SketchPayload.Backend`` enum value raises
:class:`~sketches_tpu.resilience.WireDecodeError` naming the enum and
the value -- never a silent misdecode (the same contract
``KeyMappingProto.from_proto`` carries for the ``Interpolation`` enum).

Failure modes: truncated/garbled blobs, wrong wire types, a level
outside ``[0, 64]``, a moment payload whose vector lengths disagree
with its ``k``, and backend/spec mismatches all raise
``WireDecodeError`` with the offending detail; encoding a state type
that disagrees with ``spec.backend`` raises ``SpecError``.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax

from sketches_tpu import telemetry
from sketches_tpu.backends import BACKEND_ENUM, BACKEND_NAMES
from sketches_tpu.resilience import SpecError, WireDecodeError

__all__ = [
    "payload_to_bytes",
    "payload_from_bytes",
    "windowed_to_bytes",
    "windowed_from_bytes",
]


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(blob: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    out = 0
    while True:
        if i >= len(blob):
            raise WireDecodeError(
                "SketchPayload truncated inside a varint"
            )
        b = blob[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7
        if shift > 63:
            raise WireDecodeError("SketchPayload varint overflows 64 bits")


def _field(tag: int, wire_type: int) -> bytes:
    return _varint((tag << 3) | wire_type)


def _ld(tag: int, payload: bytes) -> bytes:
    return _field(tag, 2) + _varint(len(payload)) + payload


def _packed_doubles(vals) -> bytes:
    arr = np.ascontiguousarray(np.asarray(vals, np.float64))
    return arr.tobytes()


def _moment_payload(k: int, scalars, powers, log_powers) -> bytes:
    return (
        _field(1, 0)
        + _varint(k)
        + _ld(2, _packed_doubles(scalars))
        + _ld(3, _packed_doubles(powers))
        + _ld(4, _packed_doubles(log_powers))
    )


def payload_to_bytes(spec, state) -> List[bytes]:
    """Serialize every stream of a backend state to envelope blobs.

    ``spec.backend`` picks the layout: ``dense`` delegates to the
    classic bulk encoder (byte-identical, NO envelope -- interop
    preserved); ``uniform_collapse`` wraps each stream's dense blob
    with its collapse level; ``moment`` emits the moment payload.
    Raises ``SpecError`` when the state type disagrees with the spec's
    backend (a moment state under a dense spec is a caller bug, not a
    decode problem).
    """
    from sketches_tpu.pb.wire import state_to_bytes

    backend = spec.backend
    enum = BACKEND_ENUM[backend]
    if backend == "dense":
        if not hasattr(state, "bins_pos"):
            raise SpecError(
                "dense backend serialization needs a SketchState;"
                f" got {type(state).__name__}"
            )
        return state_to_bytes(spec, state)
    if backend == "uniform_collapse":
        if not hasattr(state, "base") or not hasattr(state, "level"):
            raise SpecError(
                "uniform_collapse serialization needs an AdaptiveState;"
                f" got {type(state).__name__}"
            )
        dense_blobs = state_to_bytes(spec, state.base)
        levels = np.asarray(jax.device_get(state.level), np.int64)
        head = _field(1, 0) + _varint(enum)
        return [
            head
            + _ld(2, blob)
            + _field(3, 0)
            + _varint(int(levels[i]))
            for i, blob in enumerate(dense_blobs)
        ]
    # moment
    if not hasattr(state, "powers"):
        raise SpecError(
            "moment serialization needs a MomentState;"
            f" got {type(state).__name__}"
        )
    host = jax.device_get(
        (state.count, state.zero_count, state.neg_count, state.sum,
         state.min, state.max, state.powers, state.log_powers)
    )
    count, zero, neg, total, vmin, vmax, powers, log_powers = (
        np.asarray(x, np.float64) for x in host
    )
    k = powers.shape[-1]
    head = _field(1, 0) + _varint(enum)
    return [
        head
        + _ld(
            4,
            _moment_payload(
                k,
                [count[i], zero[i], neg[i], total[i], vmin[i], vmax[i]],
                powers[i],
                log_powers[i],
            ),
        )
        for i in range(count.shape[0])
    ]


def _skip_field(blob: bytes, i: int, wire_type: int) -> int:
    if wire_type == 0:
        _, i = _read_varint(blob, i)
        return i
    if wire_type == 1:
        return i + 8
    if wire_type == 2:
        n, i = _read_varint(blob, i)
        return i + n
    if wire_type == 5:
        return i + 4
    raise WireDecodeError(
        f"SketchPayload wire type {wire_type} unsupported"
    )


def _parse_payload(blob: bytes):
    """One envelope blob -> ``(backend_enum, dense, level, moment)``.

    Unknown fields skip (proto3 semantics); an unknown *backend enum*
    refuses loudly by name; structural damage raises
    ``WireDecodeError``.
    """
    i = 0
    backend = 0
    dense = None
    level = 0
    moment = None
    n_total = len(blob)
    while i < n_total:
        key, i = _read_varint(blob, i)
        tag, wt = key >> 3, key & 7
        if tag == 1 and wt == 0:
            backend, i = _read_varint(blob, i)
        elif tag == 2 and wt == 2:
            n, i = _read_varint(blob, i)
            if i + n > n_total:
                raise WireDecodeError(
                    "SketchPayload.dense truncated"
                )
            dense = blob[i : i + n]
            i += n
        elif tag == 3 and wt == 0:
            level, i = _read_varint(blob, i)
        elif tag == 4 and wt == 2:
            n, i = _read_varint(blob, i)
            if i + n > n_total:
                raise WireDecodeError(
                    "SketchPayload.moment truncated"
                )
            moment = blob[i : i + n]
            i += n
        else:
            i = _skip_field(blob, i, wt)
        if i > n_total:
            raise WireDecodeError("SketchPayload truncated mid-field")
    if backend not in BACKEND_NAMES:
        raise WireDecodeError(
            f"unknown SketchPayload.Backend enum value {backend}:"
            " refusing to decode (emitter is newer than this reader;"
            f" known values {sorted(BACKEND_NAMES)})"
        )
    return backend, dense, level, moment


def _parse_moment(payload: bytes):
    """MomentPayload bytes -> ``(k, scalars[6], powers[k], log_powers[k])``;
    length/structure damage raises ``WireDecodeError``."""
    i = 0
    k = None
    scalars = powers = log_powers = None
    n_total = len(payload)
    while i < n_total:
        key, i = _read_varint(payload, i)
        tag, wt = key >> 3, key & 7
        if tag == 1 and wt == 0:
            k, i = _read_varint(payload, i)
        elif tag in (2, 3, 4) and wt == 2:
            n, i = _read_varint(payload, i)
            if i + n > n_total or n % 8:
                raise WireDecodeError(
                    "MomentPayload packed-double run truncated"
                )
            arr = np.frombuffer(payload[i : i + n], np.float64)
            if tag == 2:
                scalars = arr
            elif tag == 3:
                powers = arr
            else:
                log_powers = arr
            i += n
        else:
            i = _skip_field(payload, i, wt)
    if k is None or scalars is None or powers is None or log_powers is None:
        raise WireDecodeError(
            "MomentPayload missing required fields (k/scalars/powers/"
            "log_powers)"
        )
    if scalars.shape[0] != 6 or powers.shape[0] != k \
            or log_powers.shape[0] != k:
        raise WireDecodeError(
            f"MomentPayload vector lengths disagree with k={k}:"
            f" scalars={scalars.shape[0]}, powers={powers.shape[0]},"
            f" log_powers={log_powers.shape[0]}"
        )
    return k, scalars, powers, log_powers


def _pack_blobs(blobs):
    """Concatenate ``blobs`` for a native scan -> (buf, offsets int64[n+1])."""
    n = len(blobs)
    lens = np.fromiter((len(b) for b in blobs), np.int64, n)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    return b"".join(blobs), offsets


def _native_scan_counters(n_careful: int) -> None:
    if telemetry._ACTIVE:
        telemetry.counter_inc("wire.native.decode_calls")
        if n_careful:
            telemetry.counter_inc(
                "wire.native.careful_fallbacks", float(n_careful)
            )


def payload_from_bytes(spec, blobs, *, assume_native_linear: bool = False):
    """Decode envelope (or plain dense) blobs into one backend state.

    Returns a :class:`SketchState` (dense spec), ``AdaptiveState``
    (uniform_collapse spec), or ``MomentState`` (moment spec).  Plain
    dense blobs (first byte ``0x0a``) decode through the classic bulk
    path under a dense spec.  Raises ``WireDecodeError`` for: a blob
    whose backend tag disagrees with ``spec.backend``, an unknown
    backend enum value (named loudly), structural damage, a level
    outside ``[0, spec.max_collapses]``... every refusal names the
    stream index; an empty ``blobs`` list decodes to an empty state.
    """
    import jax.numpy as jnp

    from sketches_tpu.backends import BACKEND_ENUM as ENUM

    want = spec.backend
    if want == "dense":
        from sketches_tpu.pb.wire import bytes_to_state

        for idx, blob in enumerate(blobs):
            if blob[:1] == b"\x08":
                raise WireDecodeError(
                    f"blob {idx} is a SketchPayload envelope but the"
                    " spec's backend is 'dense': decode it with the"
                    " matching backend spec"
                )
        return bytes_to_state(
            spec, blobs, assume_native_linear=assume_native_linear
        )
    if want == "uniform_collapse":
        from sketches_tpu.pb.wire import bytes_to_state

        n = len(blobs)
        dense_blobs: List[bytes] = [b""] * n
        levels: List[int] = [0] * n
        scanner = None
        if n:
            from sketches_tpu import native

            scanner = native.wire_scanner()
        if scanner is not None:
            # Native envelope split: one C++ scan extracts each
            # canonical envelope's (dense sub-blob range, level); the
            # dense sub-blobs then ride the stock bulk decode below, so
            # its telemetry/integrity/error semantics apply unchanged.
            # Careful handoffs (and levels the range gate refuses) are
            # re-examined per blob in batch order, so refusals name the
            # same first offender as the pure-Python walk.
            from sketches_tpu.native import _i64ptr, _u8ptr

            buf, offsets = _pack_blobs([bytes(b) for b in blobs])
            status = np.zeros(n, np.uint8)
            level_arr = np.zeros(n, np.int64)
            doff = np.zeros(n, np.int64)
            dlen = np.zeros(n, np.int64)
            n_careful = scanner.ddsk_wire_scan_envelope(
                buf, n, _i64ptr(offsets), ENUM[want], _u8ptr(status),
                _i64ptr(level_arr), _i64ptr(doff), _i64ptr(dlen),
            )
            if n_careful < 0:
                status[:] = 1
                n_careful = n
            _native_scan_counters(int(n_careful))
            ok = status == 0
            bad_level = ok & (
                (level_arr < 0) | (level_arr > spec.max_collapses)
            )
            for idx in np.nonzero(ok & ~bad_level)[0].tolist():
                dense_blobs[idx] = buf[doff[idx] : doff[idx] + dlen[idx]]
                levels[idx] = int(level_arr[idx])
            problems = np.nonzero(~ok | bad_level)[0].tolist()
        else:
            problems = list(range(n))
        for idx in problems:
            blob = blobs[idx]
            if scanner is not None and status[idx] == 0:
                # Native-parsed envelope whose level fails the range
                # gate: refuse with the exact walker message.
                raise WireDecodeError(
                    f"blob {idx}: collapse level {int(level_arr[idx])}"
                    f" outside [0, {spec.max_collapses}]"
                )
            backend, dense, level, _ = _parse_payload(bytes(blob))
            if backend != ENUM[want]:
                raise WireDecodeError(
                    f"blob {idx} carries backend"
                    f" {BACKEND_NAMES.get(backend, backend)!r}, spec"
                    f" wants {want!r}"
                )
            if dense is None:
                raise WireDecodeError(
                    f"blob {idx}: uniform_collapse envelope missing the"
                    " dense payload"
                )
            if not 0 <= level <= spec.max_collapses:
                raise WireDecodeError(
                    f"blob {idx}: collapse level {level} outside"
                    f" [0, {spec.max_collapses}]"
                )
            dense_blobs[idx] = dense
            levels[idx] = level
        from sketches_tpu.backends.uniform import AdaptiveState

        base = bytes_to_state(
            spec, dense_blobs, assume_native_linear=assume_native_linear
        )
        return AdaptiveState(
            base=base, level=jnp.asarray(levels, jnp.int32)
        )
    # moment
    from sketches_tpu.backends.moment import MomentState

    n = len(blobs)
    k_spec = spec.n_moments
    # Packed scalar rows: [count, zero, neg, sum, min, max] per stream;
    # the native scanner copies straight into these arrays for canonical
    # envelopes, careful blobs fill in through the Python walker below.
    scal = np.zeros((n, 6), np.float64)
    scal[:, 4] = np.inf
    scal[:, 5] = -np.inf
    powers = np.zeros((n, k_spec), np.float64)
    log_powers = np.zeros((n, k_spec), np.float64)
    scanner = None
    if n:
        from sketches_tpu import native

        scanner = native.wire_scanner()
    if scanner is not None:
        from sketches_tpu.native import _dptr, _i64ptr, _u8ptr

        buf, offsets = _pack_blobs([bytes(b) for b in blobs])
        status = np.zeros(n, np.uint8)
        n_careful = scanner.ddsk_wire_scan_moment(
            buf, n, _i64ptr(offsets), ENUM[want], k_spec, _u8ptr(status),
            _dptr(scal), _dptr(powers), _dptr(log_powers),
        )
        if n_careful < 0:
            status[:] = 1
        careful_idx = np.nonzero(status)[0].tolist()
        _native_scan_counters(len(careful_idx))
    else:
        careful_idx = list(range(n))
    for idx in careful_idx:
        blob = blobs[idx]
        backend, _, _, moment = _parse_payload(bytes(blob))
        if backend != ENUM[want]:
            raise WireDecodeError(
                f"blob {idx} carries backend"
                f" {BACKEND_NAMES.get(backend, backend)!r}, spec wants"
                f" {want!r}"
            )
        if moment is None:
            raise WireDecodeError(
                f"blob {idx}: moment envelope missing the moment payload"
            )
        k, scalars, p, lp = _parse_moment(moment)
        if k != k_spec:
            raise WireDecodeError(
                f"blob {idx}: moment payload has k={k}, spec wants"
                f" k={k_spec}"
            )
        scal[idx] = scalars
        powers[idx] = p
        log_powers[idx] = lp
    count, zero, neg, total, vmin, vmax = (
        np.ascontiguousarray(scal[:, c]) for c in range(6)
    )
    dt = np.dtype(jnp.dtype(spec.dtype).name)

    def cast(a):
        # Saturated power sums round-trip as +/-inf in the narrower
        # device dtype -- the moment backend's documented saturation
        # state, not an error.
        with np.errstate(over="ignore"):
            return jnp.asarray(a.astype(dt))
    return MomentState(
        count=cast(count),
        zero_count=cast(zero),
        neg_count=cast(neg),
        sum=cast(total),
        min=cast(vmin),
        max=cast(vmax),
        powers=cast(powers),
        log_powers=cast(log_powers),
    )



# ---------------------------------------------------------------------------
# Windowed envelope (backend enum 3: a whole ring in one blob)
# ---------------------------------------------------------------------------


def windowed_to_bytes(wsk) -> bytes:
    """Serialize a whole ``WindowedSketch`` ring -- buckets, ladder
    config, and the exact mass ledger -- to ONE envelope blob.

    The blob's first byte is the ``SketchPayload`` varint tag
    (``0x08``) with ``backend = WINDOWED``: pre-r18 readers refuse the
    unknown enum value loudly, and r18+ readers under a plain backend
    spec refuse it by name -- a windowed blob can never silently
    decode as an unwindowed sketch.  Each bucket carries one inner
    per-stream payload blob (dense / uniform / moment, byte-identical
    to :func:`payload_to_bytes` of that bucket's state).  Raises
    ``SpecError`` for a non-windowed argument or a bucket id outside
    the varint range (negative clock).
    """
    from sketches_tpu.backends import BACKEND_WINDOWED
    from sketches_tpu.windows import WindowedSketch

    if not isinstance(wsk, WindowedSketch):
        raise SpecError(
            f"windowed_to_bytes needs a WindowedSketch; got"
            f" {type(wsk).__name__} (use payload_to_bytes for plain"
            " backend states)"
        )
    spec = wsk.spec
    entries = []
    buckets = [
        (r, bid, b.state, b.mass, False)
        for r in range(wsk.config.n_rungs)
        for bid, b in sorted(wsk._rungs[r].items())
    ]
    if wsk._live_id is not None:
        buckets.append((
            0, wsk._live_id, wsk._snapshot_state(wsk._live.state),
            wsk._live_mass, True,
        ))
    for rung, bid, state, mass, live in buckets:
        if bid < 0:
            raise SpecError(
                f"bucket id {bid} is negative (clock before epoch):"
                " the windowed envelope encodes ids as varints"
            )
        entry = (
            _field(1, 0) + _varint(rung)
            + _field(2, 0) + _varint(bid)
            + _ld(3, _packed_doubles([mass]))
            + _field(4, 0) + _varint(1 if live else 0)
        )
        for blob in payload_to_bytes(spec, state):
            entry += _ld(5, blob)
        entries.append(entry)
    payload = (
        _field(1, 0) + _varint(wsk.n_streams)
        + _ld(2, _packed_doubles(wsk.config.slices_s))
        + _ld(3, _packed_doubles([float(n) for n in wsk.config.lengths]))
        + _ld(4, _packed_doubles([
            wsk._total, wsk._retired, float(wsk._rotations),
            float(wsk._ladder_collapses),
        ]))
    )
    if wsk.config.collapse_levels is not None:
        payload += _ld(
            5,
            _packed_doubles(
                [float(v) for v in wsk.config.collapse_levels]
            ),
        )
    for entry in entries:
        payload += _ld(6, entry)
    payload += _field(7, 0) + _varint(
        0 if wsk._cur is None else wsk._cur + 1
    )
    return _field(1, 0) + _varint(BACKEND_WINDOWED) + _ld(5, payload)


def _read_packed_doubles(payload: bytes, what: str) -> np.ndarray:
    if len(payload) % 8:
        raise WireDecodeError(
            f"WindowedPayload {what} packed-double run truncated"
        )
    return np.frombuffer(payload, np.float64)


def _parse_bucket_entry(entry: bytes):
    i = 0
    rung = 0
    bid = 0
    mass = None
    live = 0
    blobs: List[bytes] = []
    n_total = len(entry)
    while i < n_total:
        key, i = _read_varint(entry, i)
        tag, wt = key >> 3, key & 7
        if tag == 1 and wt == 0:
            rung, i = _read_varint(entry, i)
        elif tag == 2 and wt == 0:
            bid, i = _read_varint(entry, i)
        elif tag == 3 and wt == 2:
            n, i = _read_varint(entry, i)
            if i + n > n_total:
                raise WireDecodeError("BucketEntry.mass truncated")
            mass = _read_packed_doubles(entry[i : i + n], "mass")
            i += n
        elif tag == 4 and wt == 0:
            live, i = _read_varint(entry, i)
        elif tag == 5 and wt == 2:
            n, i = _read_varint(entry, i)
            if i + n > n_total:
                raise WireDecodeError("BucketEntry.stream truncated")
            blobs.append(entry[i : i + n])
            i += n
        else:
            i = _skip_field(entry, i, wt)
    if mass is None or mass.shape[0] != 1:
        raise WireDecodeError("BucketEntry missing its mass ledger entry")
    return rung, bid, float(mass[0]), bool(live), blobs


def windowed_from_bytes(spec, blob: bytes, *, config=None, clock=None,
                        mesh=None, value_axis=None, stream_axis=None,
                        engine: str = "auto"):
    """Decode a :func:`windowed_to_bytes` envelope -> a reconstructed
    ``WindowedSketch`` (ring, ladder, and exact ledger intact).

    ``spec`` must match the inner bucket payloads' backend exactly as
    :func:`payload_from_bytes` demands; a ``config`` passed by the
    caller is cross-checked against the encoded ladder and a mismatch
    refuses loudly.  Raises ``WireDecodeError`` for: a blob that is not
    a windowed envelope (wrong backend enum, named), structural damage,
    a bucket whose stream count disagrees with ``n_streams``, ladder
    shapes that fail ``WindowConfig`` validation; the kill switch
    (``SKETCHES_TPU_WINDOWED=0``) refuses via the ``WindowedSketch``
    constructor (``SpecError``).
    """
    from sketches_tpu.backends import BACKEND_WINDOWED
    from sketches_tpu.windows import WindowConfig, WindowedSketch, _Bucket

    i = 0
    backend = 0
    payload = None
    n_total = len(blob)
    while i < n_total:
        key, i = _read_varint(blob, i)
        tag, wt = key >> 3, key & 7
        if tag == 1 and wt == 0:
            backend, i = _read_varint(blob, i)
        elif tag == 5 and wt == 2:
            n, i = _read_varint(blob, i)
            if i + n > n_total:
                raise WireDecodeError("SketchPayload.windowed truncated")
            payload = blob[i : i + n]
            i += n
        else:
            i = _skip_field(blob, i, wt)
    if backend != BACKEND_WINDOWED:
        raise WireDecodeError(
            f"blob carries backend"
            f" {BACKEND_NAMES.get(backend, backend)!r}, expected"
            " 'windowed' (decode plain payloads with"
            " payload_from_bytes)"
        )
    if payload is None:
        raise WireDecodeError(
            "windowed envelope missing the WindowedPayload"
        )
    i = 0
    n_streams = None
    slices = lengths = ledger = levels = None
    entries: List[bytes] = []
    cur_plus1 = 0
    n_total = len(payload)
    while i < n_total:
        key, i = _read_varint(payload, i)
        tag, wt = key >> 3, key & 7
        if tag == 1 and wt == 0:
            n_streams, i = _read_varint(payload, i)
        elif tag in (2, 3, 4, 5) and wt == 2:
            n, i = _read_varint(payload, i)
            if i + n > n_total:
                raise WireDecodeError("WindowedPayload field truncated")
            arr = _read_packed_doubles(
                payload[i : i + n],
                {2: "slices_s", 3: "lengths", 4: "ledger",
                 5: "collapse_levels"}[tag],
            )
            if tag == 2:
                slices = arr
            elif tag == 3:
                lengths = arr
            elif tag == 4:
                ledger = arr
            else:
                levels = arr
            i += n
        elif tag == 6 and wt == 2:
            n, i = _read_varint(payload, i)
            if i + n > n_total:
                raise WireDecodeError("WindowedPayload bucket truncated")
            entries.append(payload[i : i + n])
            i += n
        elif tag == 7 and wt == 0:
            cur_plus1, i = _read_varint(payload, i)
        else:
            i = _skip_field(payload, i, wt)
    if n_streams is None or slices is None or lengths is None \
            or ledger is None or ledger.shape[0] < 2:
        raise WireDecodeError(
            "WindowedPayload missing required fields"
            " (n_streams/slices_s/lengths/ledger)"
        )
    try:
        decoded_config = WindowConfig(
            slices_s=tuple(float(s) for s in slices),
            lengths=tuple(int(n) for n in lengths),
            collapse_levels=(
                None if levels is None
                else tuple(int(v) for v in levels)
            ),
        )
    except SpecError as e:
        raise WireDecodeError(
            f"windowed envelope carries an invalid ladder: {e}"
        ) from e
    if config is not None and config != decoded_config:
        raise WireDecodeError(
            "windowed envelope ladder disagrees with the caller's"
            f" config: encoded {decoded_config}, wanted {config}"
        )
    wsk = WindowedSketch(
        int(n_streams), spec=spec, config=decoded_config, clock=clock,
        mesh=mesh, value_axis=value_axis, stream_axis=stream_axis,
        engine=engine,
    )
    for entry in entries:
        rung, bid, mass, live, stream_blobs = _parse_bucket_entry(entry)
        if rung >= decoded_config.n_rungs:
            raise WireDecodeError(
                f"bucket rung {rung} outside the {decoded_config.n_rungs}"
                "-rung ladder"
            )
        if len(stream_blobs) != int(n_streams):
            raise WireDecodeError(
                f"bucket (rung {rung}, id {bid}) carries"
                f" {len(stream_blobs)} stream payloads, expected"
                f" {int(n_streams)}"
            )
        state = payload_from_bytes(spec, stream_blobs)
        if live:
            wsk._set_live_state(state)
            wsk._live_id = bid
            wsk._live_mass = mass
        else:
            wsk._rungs[rung][bid] = _Bucket(
                rung=rung, id=bid, state=state, mass=mass
            )
    wsk._total = float(ledger[0])
    wsk._retired = float(ledger[1])
    if ledger.shape[0] >= 4:
        wsk._rotations = int(ledger[2])
        wsk._ladder_collapses = int(ledger[3])
    wsk._cur = None if cur_plus1 == 0 else int(cur_plus1 - 1)
    # The rungs were assigned behind the constructor's back; the wire
    # format never carries the two-stacks aggregates (derived state),
    # so drop the fresh stacks and let the first plan rebuild them.
    wsk._agg_invalidate()
    return wsk
