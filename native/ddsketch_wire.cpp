// Native bulk wire codec: structural-template decode over packed blob
// arrays (ROADMAP item 1 -- the 100k-decode <= 1 s letter).
//
// The Python canonical walker (sketches_tpu/pb/wire.py::_parse_canonical)
// is the semantic oracle: this scanner accepts AT MOST what that walker
// accepts, extracts byte-identical facts (payload doubles, zigzag-decoded
// sint32 store offsets truncated to 32 bits, trailing zeroCount), and
// hands ANYTHING else back to Python blob-by-blob via a per-blob status
// ("careful-path handoff contract", docs/DESIGN.md section 17).  Being
// conservative is always safe -- a careful blob decodes through the
// protobuf reference path with identical placement semantics -- so every
// branch below errs toward status != 0 rather than guessing.
//
// Framing invariants assumed for a status-0 (fast-path) dense blob:
//   * blob starts with the caller's expected serialized `mapping` field
//     (memcmp-equal bytes -- this certifies the spec's mapping);
//   * at most one positiveValues (0x12) and one negativeValues (0x1a)
//     store field, each `<len> [0x12 <plen> <packed doubles>
//     [0x18 <zigzag sint32 offset>]]`, plen a multiple of 8, the offset
//     varint (when present) ending exactly at the store body's end;
//   * any number of trailing/interleaved zeroCount (0x21) doubles, last
//     one winning (protobuf scalar-field semantics);
//   * every declared length lands inside the blob (a truncated blob is
//     a careful blob -- protobuf's DecodeError must fire, never a
//     silent slice-clamp);
//   * varints may be non-minimal; values with significant bits past 64
//     are treated as "huge" and fail any length check (matching Python's
//     arbitrary-precision comparison), while the store-offset varint
//     truncates to its low 32 bits before zigzag decode (protobuf sint32
//     semantics, ADVICE r5 item 1).
//
// Payload doubles are memcpy'd little-endian into the caller's aligned
// staging buffer (the wire format is LE; this scanner assumes an LE
// host, which the ctypes loader asserts before enabling it).
//
// ABI: every symbol here is versioned through ddsk_wire_abi_version();
// the Python loader refuses the fast path (degrading to the pure-Python
// walker, never corrupting) when the constant disagrees -- a stale .so
// built from older sources answers the old version number.  Bump
// kWireAbiVersion on ANY signature or output-layout change.
//
// Build: `make -C native` links this into libddsketch_host.so alongside
// the host-tier engine (plain C ABI, no pybind11).

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

constexpr int kWireAbiVersion = 1;

// Per-blob scan statuses (the Python side folds 1/2/3 into "careful").
enum Status : uint8_t {
  kOk = 0,
  kCarefulForeign = 1,   // prefix/envelope mismatch: foreign or damaged
  kCarefulTemplate = 2,  // prefix matched, structure deviated
  kPreMarked = 3,        // caller pre-marked (over admission cap): skip
};

struct Varint {
  uint64_t value;  // low 64 bits
  bool huge;       // significant bits at/above 2^64 were dropped
  bool ok;         // terminated inside [pos, end)
  size_t next;
};

// Reads one varint; mirrors Python's arbitrary-precision read in the only
// two ways callers consume it: exact low 64 bits, plus a "huge" flag so
// length comparisons treat >= 2^64 values as larger than any blob.
Varint read_varint(const uint8_t* p, size_t pos, size_t end) {
  Varint r{0, false, false, pos};
  uint64_t v = 0;
  bool huge = false;
  int shift = 0;
  while (pos < end) {
    const uint8_t b = p[pos++];
    const uint64_t bits = b & 0x7F;
    if (shift < 64) {
      if (shift > 57 && (bits >> (64 - shift)) != 0) huge = true;
      v |= bits << shift;
    } else if (bits != 0) {
      huge = true;
    }
    if (!(b & 0x80)) {
      r.value = v;
      r.huge = huge;
      r.ok = true;
      r.next = pos;
      return r;
    }
    shift += 7;
  }
  return r;  // ran off the end mid-varint
}

struct Run {
  size_t payload_off = 0;  // absolute byte offset of the packed doubles
  long long len8 = 0;      // trimmed run length, in doubles (0 = no run)
  long long j0 = 0;        // window start: decoded key offset - base
};

// Walks one canonical blob body past its mapping prefix; the exact
// mirror of pb/wire.py::_parse_canonical.  Returns false for ANY
// non-canonical shape (careful-path handoff).
bool scan_dense_body(const uint8_t* buf, size_t pos, size_t end,
                     long long base, Run runs[2], double* zc) {
  int seen = 0;  // bit 0 = positiveValues parsed, bit 1 = negativeValues
  *zc = 0.0;
  runs[0] = Run();
  runs[1] = Run();
  size_t j = pos;
  while (j < end) {
    const uint8_t tag = buf[j];
    if (tag == 0x12 || tag == 0x1A) {
      const int which = (tag == 0x1A) ? 1 : 0;
      const int bit = which ? 2 : 1;
      if ((seen & bit) || j + 1 >= end) return false;
      seen |= bit;
      const Varint ln = read_varint(buf, j + 1, end);
      if (!ln.ok || ln.huge || ln.value > (uint64_t)(end - ln.next)) {
        return false;  // declared length leaves the blob
      }
      const size_t end_body = ln.next + (size_t)ln.value;
      j = ln.next;
      if (ln.value == 0) continue;  // canonical empty store submessage
      if (buf[j] != 0x12 || j + 1 >= end_body) return false;
      const Varint pl = read_varint(buf, j + 1, end);
      if (!pl.ok || pl.huge || (pl.value & 7) ||
          pl.value > (uint64_t)(end - pl.next)) {
        return false;
      }
      const size_t p0 = pl.next;
      const size_t pend = p0 + (size_t)pl.value;
      if (pend > end_body) return false;
      long long key_off = 0;
      if (pend < end_body) {
        if (buf[pend] != 0x18 || pend + 1 >= end_body) return false;
        const Varint z = read_varint(buf, pend + 1, end);
        if (!z.ok || z.next != end_body) return false;
        // Protobuf sint32: truncate to the low 32 bits, then zigzag.
        const uint32_t zm = (uint32_t)(z.value & 0xFFFFFFFFull);
        key_off = (long long)(zm >> 1) ^ -(long long)(zm & 1);
      }
      // Trim the run's trailing all-zero chunk padding at the
      // 8-byte-rounded cut (a double with any nonzero byte survives
      // whole) -- same rstrip discipline as the Python walker.
      size_t kept = pend;
      while (kept > p0 && buf[kept - 1] == 0) --kept;
      const long long t_len = (long long)((kept - p0 + 7) >> 3);
      if (t_len) {
        runs[which].payload_off = p0;
        runs[which].len8 = t_len;
        runs[which].j0 = key_off - base;
      }
      j = end_body;
    } else if (tag == 0x21) {  // zeroCount double (last occurrence wins)
      if (j + 9 > end) return false;
      std::memcpy(zc, buf + j + 1, 8);
      j += 9;
    } else {
      return false;
    }
  }
  return true;
}

}  // namespace

extern "C" {

int ddsk_wire_abi_version() { return kWireAbiVersion; }

// Structural scan of `n` packed dense blobs.
//
//   buf        concatenated blob bytes
//   offsets    int64[n+1] blob boundaries into buf
//   prefix     the expected serialized `mapping` field bytes
//   base       spec.key_offset (window starts are returned relative to it)
//   status     uint8[n] in/out: nonzero entries on entry are skipped
//              (caller pre-marked, e.g. over the admission cap); on exit
//              0 = fast-parsed, nonzero = careful-path handoff
//   zc         double[n] out: zeroCount per fast-parsed blob (0 if absent)
//   run_pos    int64[2n] out: start of each run's doubles in payload_out
//              (slot 2i = positive store, 2i+1 = negative store)
//   run_len    int64[2n] out: trimmed run length in doubles (0 = no run)
//   run_j0     int64[2n] out: window start (decoded key offset - base)
//   payload_out double[] out: aligned staging; capacity must be at least
//              (offsets[n] / 8) doubles (trimmed payloads can never
//              exceed the input bytes)
//
// Returns the number of careful blobs, or -1 on invalid arguments.
long long ddsk_wire_scan_dense(const uint8_t* buf, long long n,
                               const long long* offsets,
                               const uint8_t* prefix, long long prefix_len,
                               long long base, uint8_t* status, double* zc,
                               long long* run_pos, long long* run_len,
                               long long* run_j0, double* payload_out) {
  if (n < 0 || prefix_len < 0) return -1;
  long long careful = 0;
  long long cursor = 0;  // doubles written into payload_out
  for (long long i = 0; i < n; ++i) {
    run_pos[2 * i] = run_pos[2 * i + 1] = 0;
    run_len[2 * i] = run_len[2 * i + 1] = 0;
    run_j0[2 * i] = run_j0[2 * i + 1] = 0;
    zc[i] = 0.0;
    if (status[i]) {  // pre-marked by the caller: hands off untouched
      ++careful;
      continue;
    }
    const long long a = offsets[i], b = offsets[i + 1];
    if (b - a < prefix_len ||
        std::memcmp(buf + a, prefix, (size_t)prefix_len) != 0) {
      status[i] = kCarefulForeign;
      ++careful;
      continue;
    }
    Run runs[2];
    double z;
    if (!scan_dense_body(buf, (size_t)(a + prefix_len), (size_t)b, base,
                         runs, &z)) {
      status[i] = kCarefulTemplate;
      ++careful;
      continue;
    }
    zc[i] = z;
    for (int w = 0; w < 2; ++w) {
      if (runs[w].len8 <= 0) continue;
      std::memcpy(payload_out + cursor, buf + runs[w].payload_off,
                  (size_t)runs[w].len8 * 8);
      run_pos[2 * i + w] = cursor;
      run_len[2 * i + w] = runs[w].len8;
      run_j0[2 * i + w] = runs[w].j0;
      cursor += runs[w].len8;
    }
  }
  return careful;
}

// Splits `n` packed SketchPayload envelopes of the emitter's canonical
// uniform_collapse shape -- `0x08 <backend> 0x12 <len> <dense blob>
// 0x18 <level>`, nothing else, ending exactly at the blob end -- into
// per-blob (dense sub-blob range, collapse level).  The dense sub-blob
// is NOT scanned here: the caller feeds the ranges back through the
// dense bulk decode (which itself dispatches to ddsk_wire_scan_dense),
// so telemetry/integrity/error semantics stay byte-identical with the
// Python path.  Any deviation -- wrong backend enum, reordered or
// unknown fields, truncation, a level varint past 2^31 (Python formats
// the exact value in its refusal) -- is a careful handoff.
//
// Outputs: status uint8[n] (in/out, as above), level int64[n],
// dense_off/dense_len int64[n] (absolute byte range into buf).
// Returns the number of careful blobs, or -1 on invalid arguments.
long long ddsk_wire_scan_envelope(const uint8_t* buf, long long n,
                                  const long long* offsets,
                                  long long expected_backend,
                                  uint8_t* status, long long* level,
                                  long long* dense_off,
                                  long long* dense_len) {
  if (n < 0) return -1;
  long long careful = 0;
  for (long long i = 0; i < n; ++i) {
    level[i] = 0;
    dense_off[i] = 0;
    dense_len[i] = 0;
    if (status[i]) {
      ++careful;
      continue;
    }
    const size_t a = (size_t)offsets[i], b = (size_t)offsets[i + 1];
    size_t j = a;
    bool ok = false;
    do {
      if (j >= b || buf[j] != 0x08) break;
      const Varint backend = read_varint(buf, j + 1, b);
      if (!backend.ok || backend.huge ||
          backend.value != (uint64_t)expected_backend) {
        break;
      }
      j = backend.next;
      if (j >= b || buf[j] != 0x12) break;
      const Varint ln = read_varint(buf, j + 1, b);
      if (!ln.ok || ln.huge || ln.value > (uint64_t)(b - ln.next)) break;
      const size_t d0 = ln.next, d1 = ln.next + (size_t)ln.value;
      j = d1;
      if (j >= b || buf[j] != 0x18) break;
      const Varint lv = read_varint(buf, j + 1, b);
      // Levels past 2^31 hand off so Python can format the true value
      // in its range refusal.
      if (!lv.ok || lv.huge || lv.value > 0x7FFFFFFFull) break;
      if (lv.next != b) break;  // canonical envelopes end at the level
      level[i] = (long long)lv.value;
      dense_off[i] = (long long)d0;
      dense_len[i] = (long long)(d1 - d0);
      ok = true;
    } while (false);
    if (!ok) {
      status[i] = kCarefulForeign;
      ++careful;
    }
  }
  return careful;
}

// Scans `n` packed moment-backend SketchPayload envelopes of the
// emitter's canonical shape -- `0x08 <backend> 0x22 <len>` wrapping a
// MomentPayload `0x08 <k> 0x12 48 <6 doubles> 0x1a <8k> <k doubles>
// 0x22 <8k> <k doubles>`, both ending exactly where declared -- and
// copies the values straight into the caller's arrays.  A k that
// disagrees with the spec's, or any structural deviation, hands off
// (Python raises its exact k-mismatch/structure refusal).
//
// Outputs: status uint8[n] (in/out), scalars double[n*6]
// (count/zero/neg/sum/min/max rows), powers/log_powers double[n*k].
// Careful rows are left untouched (the caller pre-fills defaults).
// Returns the number of careful blobs, or -1 on invalid arguments.
long long ddsk_wire_scan_moment(const uint8_t* buf, long long n,
                                const long long* offsets,
                                long long expected_backend, long long k,
                                uint8_t* status, double* scalars,
                                double* powers, double* log_powers) {
  if (n < 0 || k < 0) return -1;
  long long careful = 0;
  const uint64_t k8 = (uint64_t)k * 8;
  for (long long i = 0; i < n; ++i) {
    if (status[i]) {
      ++careful;
      continue;
    }
    const size_t a = (size_t)offsets[i], b = (size_t)offsets[i + 1];
    size_t j = a;
    bool ok = false;
    do {
      if (j >= b || buf[j] != 0x08) break;
      const Varint backend = read_varint(buf, j + 1, b);
      if (!backend.ok || backend.huge ||
          backend.value != (uint64_t)expected_backend) {
        break;
      }
      j = backend.next;
      if (j >= b || buf[j] != 0x22) break;
      const Varint ln = read_varint(buf, j + 1, b);
      if (!ln.ok || ln.huge || ln.value > (uint64_t)(b - ln.next)) break;
      const size_t mend = ln.next + (size_t)ln.value;
      j = ln.next;
      if (mend != b) break;  // canonical envelopes end at the payload
      // MomentPayload: k, then the three packed-double runs in order.
      if (j >= mend || buf[j] != 0x08) break;
      const Varint kv = read_varint(buf, j + 1, mend);
      if (!kv.ok || kv.huge || kv.value != (uint64_t)k) break;
      j = kv.next;
      if (j >= mend || buf[j] != 0x12) break;
      const Varint sl = read_varint(buf, j + 1, mend);
      if (!sl.ok || sl.value != 48 || 48 > (uint64_t)(mend - sl.next)) break;
      const size_t s0 = sl.next;
      j = s0 + 48;
      if (j >= mend || buf[j] != 0x1A) break;
      const Varint pw = read_varint(buf, j + 1, mend);
      if (!pw.ok || pw.huge || pw.value != k8 ||
          k8 > (uint64_t)(mend - pw.next)) {
        break;
      }
      const size_t p0 = pw.next;
      j = p0 + (size_t)k8;
      if (j >= mend || buf[j] != 0x22) break;
      const Varint lw = read_varint(buf, j + 1, mend);
      if (!lw.ok || lw.huge || lw.value != k8 ||
          k8 > (uint64_t)(mend - lw.next)) {
        break;
      }
      const size_t l0 = lw.next;
      if (l0 + (size_t)k8 != mend) break;  // payload ends at log_powers
      std::memcpy(scalars + i * 6, buf + s0, 48);
      std::memcpy(powers + i * k, buf + p0, (size_t)k8);
      std::memcpy(log_powers + i * k, buf + l0, (size_t)k8);
      ok = true;
    } while (false);
    if (!ok) {
      status[i] = kCarefulForeign;
      ++careful;
    }
  }
  return careful;
}

}  // extern "C"
