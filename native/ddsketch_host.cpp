// Native host-tier DDSketch engine.
//
// The reference implementation is pure Python (SURVEY.md section 2: native
// components NONE), so this is new TPU-framework runtime code, not a port:
// the host-side ingest/query engine for places the device tier cannot be
// (data-loader threads, collector agents, pre-aggregation before device
// upload).  Semantics deliberately mirror the *device* tier
// (sketches_tpu/batched.py): a static bin window [key_offset,
// key_offset + n_bins) with clamp-to-edge collapse and collapse-mass
// counters, so a native sketch's bins can be copied verbatim into a batched
// device state.
//
// Build: `make -C native` (plain C ABI; loaded via ctypes from
// sketches_tpu/native.py -- no pybind11 dependency).

#include <algorithm>
#include <cmath>
#include <cstddef>
#include <cstdint>
#include <limits>
#include <vector>

namespace {

// Mirrors sketches_tpu/mapping.py's registry order (the Python oracle):
// the host pre-aggregator must key values identically to whichever mapping
// the device batch it feeds was built with -- including the cubic mapping
// the flagship 1M-stream config uses (VERDICT r2 item 5).
enum MappingKind {
  kLogarithmic = 0,
  kLinearInterpolated = 1,
  kCubicInterpolated = 2,
  kQuadraticInterpolated = 3,
};

// Cubic-interpolation coefficients (mapping.py . CubicallyInterpolatedMapping).
constexpr double kCubicA = 6.0 / 35.0;
constexpr double kCubicB = -3.0 / 5.0;
constexpr double kCubicC = 10.0 / 7.0;
constexpr int kNewtonIters = 5;

struct Sketch {
  int n_bins;
  int key_offset;
  int mapping;        // MappingKind
  double gamma;
  double multiplier;  // 1 / ln(gamma), cubic-scaled by 7/10 (see create)
  std::vector<double> pos;
  std::vector<double> neg;
  double zero_count = 0.0;
  double count = 0.0;
  double sum = 0.0;
  double min = std::numeric_limits<double>::infinity();
  double max = -std::numeric_limits<double>::infinity();
  double collapsed_low = 0.0;
  double collapsed_high = 0.0;
};

inline double cubic(double s) {
  return ((kCubicA * s + kCubicB) * s + kCubicC) * s;
}

inline double cubic_deriv(double s) {
  return (3.0 * kCubicA * s + 2.0 * kCubicB) * s + kCubicC;
}

// log_gamma(v) for v > 0: the (possibly approximated) log the key rounds up
// from.  Semantics are scalar-path mapping.py: frexp mantissa in [0.5, 1).
inline double log_gamma(const Sketch& s, double v) {
  switch (s.mapping) {
    case kLinearInterpolated: {
      int e;
      const double m = std::frexp(v, &e);
      return (2.0 * m - 1.0 + (e - 1)) * s.multiplier;
    }
    case kCubicInterpolated: {
      int e;
      const double m = std::frexp(v, &e);
      return (cubic(2.0 * m - 1.0) + (e - 1)) * s.multiplier;
    }
    case kQuadraticInterpolated: {
      // mapping.py . QuadraticallyInterpolatedMapping: f(t) = t*(4-t)/3.
      int e;
      const double m = std::frexp(v, &e);
      const double t = 2.0 * m - 1.0;
      return (t * (4.0 - t) / 3.0 + (e - 1)) * s.multiplier;
    }
    default:
      return std::log(v) * s.multiplier;
  }
}

// Exact inverse of log_gamma (mapping.py _pow_gamma): the bucket decode.
inline double pow_gamma(const Sketch& s, double x) {
  const double v = x / s.multiplier;
  switch (s.mapping) {
    case kLinearInterpolated: {
      const double e = std::floor(v);
      const double m = (v - e + 1.0) / 2.0;
      return std::ldexp(m, static_cast<int>(e) + 1);
    }
    case kCubicInterpolated: {
      const double e = std::floor(v);
      const double rem = v - e;
      double t = rem;  // f(t) ~= t to first order; Newton polishes
      for (int i = 0; i < kNewtonIters; ++i) {
        t = t - (cubic(t) - rem) / cubic_deriv(t);
      }
      return std::ldexp((t + 1.0) / 2.0, static_cast<int>(e) + 1);
    }
    case kQuadraticInterpolated: {
      // Closed-form inverse of t*(4-t)/3 = rem on [0, 1).
      const double e = std::floor(v);
      const double rem = v - e;
      const double t = 2.0 - std::sqrt(4.0 - 3.0 * rem);
      return std::ldexp((t + 1.0) / 2.0, static_cast<int>(e) + 1);
    }
    default:
      return std::exp(v);
  }
}

// Bucket representative: pow_gamma scaled to the alpha-midpoint.
inline double key_value(const Sketch& s, int key) {
  return pow_gamma(s, static_cast<double>(key)) * (2.0 / (1.0 + s.gamma));
}

// Clamp in DOUBLE space before any int cast: log(inf) and huge finite
// values overflow int, and an out-of-range double->int cast is UB (x86
// yields INT_MIN, which would invert the collapse direction).
inline int clamp_key(const Sketch& s, double dkey, bool* low, bool* high) {
  const double lo = static_cast<double>(s.key_offset);
  const double hi = static_cast<double>(s.key_offset + s.n_bins - 1);
  if (dkey < lo) {
    *low = true;
    return s.key_offset;
  }
  if (dkey > hi) {
    *high = true;
    return s.key_offset + s.n_bins - 1;
  }
  return static_cast<int>(dkey);
}

inline void add_one(Sketch& s, double v, double w) {
  if (w <= 0.0) return;  // inert padding, matching the device tier
  if (v > 0.0) {
    bool low = false, high = false;
    int key = clamp_key(s, std::ceil(log_gamma(s, v)), &low, &high);
    s.pos[key - s.key_offset] += w;
    if (low) s.collapsed_low += w;
    if (high) s.collapsed_high += w;
  } else if (v < 0.0) {
    bool low = false, high = false;
    int key = clamp_key(s, std::ceil(log_gamma(s, -v)), &low, &high);
    s.neg[key - s.key_offset] += w;
    if (low) s.collapsed_low += w;
    if (high) s.collapsed_high += w;
  } else if (v == 0.0 || v != v) {  // zero or NaN -> zero bucket
    s.zero_count += w;
  }
  s.count += w;
  s.sum += v * w;
  if (v < s.min) s.min = v;
  if (v > s.max) s.max = v;
}

}  // namespace

extern "C" {

void* sketch_create(double relative_accuracy, int n_bins, int key_offset,
                    int mapping_kind) {
  if (relative_accuracy <= 0.0 || relative_accuracy >= 1.0 || n_bins < 2 ||
      mapping_kind < kLogarithmic || mapping_kind > kQuadraticInterpolated) {
    return nullptr;
  }
  auto* s = new Sketch();
  s->n_bins = n_bins;
  s->key_offset = key_offset;
  s->mapping = mapping_kind;
  const double mantissa =
      2.0 * relative_accuracy / (1.0 - relative_accuracy);
  s->gamma = 1.0 + mantissa;
  s->multiplier = 1.0 / std::log1p(mantissa);
  if (mapping_kind == kCubicInterpolated) {
    // Bucket-width guarantee for the cubic log2 approximation
    // (mapping.py: multiplier *= 7/10 -- the f'(0) * ln2 derivative bound).
    s->multiplier *= 7.0 / 10.0;
  } else if (mapping_kind == kQuadraticInterpolated) {
    // Quadratic bucket-width guarantee: kappa = 3/4 (endpoint-equalized
    // max-min of f'(t)*(1+t) -- mapping.py's forcing argument).
    s->multiplier *= 3.0 / 4.0;
  }
  s->pos.assign(n_bins, 0.0);
  s->neg.assign(n_bins, 0.0);
  return s;
}

void sketch_destroy(void* handle) { delete static_cast<Sketch*>(handle); }

void sketch_add(void* handle, double value, double weight) {
  add_one(*static_cast<Sketch*>(handle), value, weight);
}

void sketch_add_batch(void* handle, const double* values,
                      const double* weights, size_t n) {
  Sketch& s = *static_cast<Sketch*>(handle);
  if (weights == nullptr) {
    for (size_t i = 0; i < n; ++i) add_one(s, values[i], 1.0);
  } else {
    for (size_t i = 0; i < n; ++i) add_one(s, values[i], weights[i]);
  }
}

// Value at quantile q, or NaN for invalid q / empty sketch.  Mirrors
// BaseDDSketch.get_quantile_value (ddsketch.py) on the static window.
double sketch_quantile(void* handle, double q) {
  const Sketch& s = *static_cast<Sketch*>(handle);
  if (q < 0.0 || q > 1.0 || s.count == 0.0) {
    return std::numeric_limits<double>::quiet_NaN();
  }
  double neg_count = 0.0;
  for (double b : s.neg) neg_count += b;
  const double rank = q * (s.count - 1.0);
  if (rank < neg_count) {
    // lower=False walk from the top of the negative store.
    const double target = neg_count - 1.0 - rank;
    double running = 0.0;
    for (int i = 0; i < s.n_bins; ++i) {
      running += s.neg[i];
      if (running >= target + 1.0) {
        return -key_value(s, i + s.key_offset);
      }
    }
    return -key_value(s, s.n_bins - 1 + s.key_offset);
  }
  if (rank < neg_count + s.zero_count) return 0.0;
  const double target = rank - neg_count - s.zero_count;
  double running = 0.0;
  for (int i = 0; i < s.n_bins; ++i) {
    running += s.pos[i];
    if (running > target) {
      return key_value(s, i + s.key_offset);
    }
  }
  return key_value(s, s.n_bins - 1 + s.key_offset);
}

// Fold `other` into `handle`; both must share (gamma, n_bins, key_offset) --
// the caller checks, we only verify shape to stay memory-safe.
int sketch_merge(void* handle, const void* other) {
  Sketch& a = *static_cast<Sketch*>(handle);
  const Sketch& b = *static_cast<const Sketch*>(other);
  if (a.n_bins != b.n_bins || a.key_offset != b.key_offset ||
      a.mapping != b.mapping) {
    return -1;
  }
  for (int i = 0; i < a.n_bins; ++i) {
    a.pos[i] += b.pos[i];
    a.neg[i] += b.neg[i];
  }
  a.zero_count += b.zero_count;
  a.count += b.count;
  a.sum += b.sum;
  a.min = std::min(a.min, b.min);
  a.max = std::max(a.max, b.max);
  a.collapsed_low += b.collapsed_low;
  a.collapsed_high += b.collapsed_high;
  return 0;
}

// Counter accessors (order: zero, count, sum, min, max, clow, chigh).
void sketch_counters(void* handle, double* out7) {
  const Sketch& s = *static_cast<Sketch*>(handle);
  out7[0] = s.zero_count;
  out7[1] = s.count;
  out7[2] = s.sum;
  out7[3] = s.min;
  out7[4] = s.max;
  out7[5] = s.collapsed_low;
  out7[6] = s.collapsed_high;
}

void sketch_bins(void* handle, double* out_pos, double* out_neg) {
  const Sketch& s = *static_cast<Sketch*>(handle);
  std::copy(s.pos.begin(), s.pos.end(), out_pos);
  std::copy(s.neg.begin(), s.neg.end(), out_neg);
}

void sketch_load_bins(void* handle, const double* pos, const double* neg,
                      const double* counters7) {
  Sketch& s = *static_cast<Sketch*>(handle);
  std::copy(pos, pos + s.n_bins, s.pos.begin());
  std::copy(neg, neg + s.n_bins, s.neg.begin());
  s.zero_count = counters7[0];
  s.count = counters7[1];
  s.sum = counters7[2];
  s.min = counters7[3];
  s.max = counters7[4];
  s.collapsed_low = counters7[5];
  s.collapsed_high = counters7[6];
}

}  // extern "C"
